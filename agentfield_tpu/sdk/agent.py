"""The agent-developer SDK: ``Agent``, ``@reasoner``/``@skill``, ``call()``,
``ai()``.

Re-design of the reference's Agent core (sdk/python/agentfield/agent.py:305:
a FastAPI subclass whose decorators synthesize pydantic input models, HTTP
endpoints and tracked wrappers; serve() registers with the control plane and
heartbeats). Differences, deliberate:

- aiohttp instead of FastAPI (toolchain), same decorator ergonomics.
- ``ai()`` routes to an in-tree TPU model node through the control plane
  (reference delegates to litellm/external providers, agent_ai.py:342) —
  no external LLM API in the loop.
- The 202-ack + status-callback contract is identical in spirit to the
  reference (agent.py:1182-1197: spawn task, ack, POST status later).
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import inspect
import json
import os
from typing import Any, Callable

import random

import aiohttp
import pydantic
from aiohttp import web

from agentfield_tpu.logging import get_logger
from agentfield_tpu.sdk.client import ControlPlaneClient, ControlPlaneError
from agentfield_tpu.sdk.context import (
    ExecutionContext,
    current_context,
    reset_context,
    set_context,
)

log = get_logger("sdk.agent")

# Backpressure backoff bounds (docs/FAULT_TOLERANCE.md overload control):
# a server Retry-After hint wins over the local exponential schedule, but a
# confused server must not park clients for an hour. The cap is DELIBERATELY
# tighter than the gateway's own 120s hint ceiling: past 30s of advertised
# wait, ai()'s failover loop is better served trying another candidate (or
# surfacing the overload) than parking on one node's estimate.
_RETRY_AFTER_CAP_S = 30.0
_BACKOFF_CAP_S = 5.0


def _backpressure_delay(attempts: int, retry_after: float | None = None) -> float:
    """Seconds to wait before retrying a 429/503 (or QueueFullError-failed)
    call. The server's Retry-After hint is authoritative when present —
    jittered UPWARD only (retrying before the server's own estimate just
    buys another 429, and multiplicative spread breaks up the herd that got
    the same hint), then capped: the cap is the true maximum sleep, jitter
    included. Without a hint: capped exponential with half-jitter, so
    patience still grows with consecutive rejections."""
    if retry_after is not None and retry_after > 0:
        # "Retry-After: 0" (RFC-legal from proxies) is NOT an invitation to
        # hot-loop an overloaded server — a non-positive hint falls through
        # to the exponential schedule below, which always sleeps.
        return min(retry_after * random.uniform(1.0, 1.25), _RETRY_AFTER_CAP_S)
    base = min(0.2 * (2**attempts), _BACKOFF_CAP_S)
    return random.uniform(base / 2, base)

DEFAULT_CONTROL_PLANE = os.environ.get("AGENTFIELD_URL", "http://127.0.0.1:8800")


def _schema_from_signature(fn: Callable) -> tuple[type[pydantic.BaseModel], dict, list[str]]:
    """Synthesize a pydantic input model from the function signature
    (reference builds InputSchema the same way, agent.py:1150-1162).
    Parameters named ctx/context receive the current ExecutionContext at
    invocation instead of appearing in the schema."""
    fields: dict[str, Any] = {}
    ctx_params: list[str] = []
    for name, p in inspect.signature(fn).parameters.items():
        if name == "self":
            continue
        if name in ("ctx", "context"):
            ctx_params.append(name)
            continue
        ann = p.annotation if p.annotation is not inspect.Parameter.empty else Any
        default = p.default if p.default is not inspect.Parameter.empty else ...
        fields[name] = (ann, default)
    model = pydantic.create_model(f"{fn.__name__}_Input", **fields)
    return model, model.model_json_schema(), ctx_params


@dataclasses.dataclass(frozen=True)
class AIConfig:
    """Default ai() parameters, merged hierarchically: agent-level <
    reasoner-level < explicit call-site arguments (reference AIConfig merge,
    agent_ai.py:189-215). None fields are "unset" and defer to the next
    level down; unset everywhere falls back to ai()'s builtin defaults."""

    model: str | None = None
    max_new_tokens: int | None = None
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    stop_token_ids: tuple[int, ...] | None = None
    timeout: float | None = None
    context_overflow: str | None = None
    output: str | None = None

    def overrides(self) -> dict[str, Any]:
        return {
            k: v for k, v in dataclasses.asdict(self).items() if v is not None
        }


def _norm_ai_defaults(v: "AIConfig | dict | None", where: str) -> "AIConfig | None":
    if v is None or isinstance(v, AIConfig):
        return v
    if isinstance(v, dict):
        known = {f.name for f in dataclasses.fields(AIConfig)}
        bad = set(v) - known
        if bad:
            raise ValueError(
                f"{where}: unknown ai_defaults keys {sorted(bad)}; "
                f"known: {sorted(known)}"
            )
        return AIConfig(**v)
    raise TypeError(f"{where}: ai_defaults must be AIConfig or dict, got {type(v).__name__}")


# The component currently executing on this task (set around dispatch) —
# how ai() finds the reasoner-level AIConfig without threading it through
# every call site.
_current_component: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "agentfield_current_component", default=None
)


class ComponentDef:
    def __init__(
        self, id: str, kind: str, fn: Callable, description: str,
        ai_defaults: "AIConfig | dict | None" = None,
    ):
        self.id = id
        self.kind = kind  # "reasoner" | "skill"
        self.fn = fn
        self.description = description
        self.ai_defaults = _norm_ai_defaults(ai_defaults, f"{kind} {id!r}")
        self.input_model, self.input_schema, self.ctx_params = _schema_from_signature(fn)
        self._passthrough = False

    @classmethod
    def passthrough(
        cls, id: str, kind: str, handler: Callable, description: str, input_schema: dict
    ) -> "ComponentDef":
        """Component with an externally-supplied JSON schema whose handler
        receives the raw payload dict (MCP tools: the server owns validation)."""
        comp = object.__new__(cls)
        comp.id = id
        comp.kind = kind
        comp.fn = handler
        comp.description = description
        comp.ai_defaults = None
        comp.input_model = None
        comp.input_schema = input_schema
        comp.ctx_params = []
        comp._passthrough = True
        return comp

    async def invoke(self, payload: Any, ctx: "ExecutionContext | None" = None) -> Any:
        if self._passthrough:
            if payload is not None and not isinstance(payload, dict):
                raise TypeError(
                    f"{self.id}: payload must be a JSON object of tool arguments, "
                    f"got {type(payload).__name__}"
                )
            result = self.fn(payload or {})
            if inspect.isawaitable(result):
                result = await result
            return result
        if isinstance(payload, dict):
            kwargs = dict(self.input_model(**payload))
        elif payload is None:
            kwargs = dict(self.input_model())
        else:
            required = [
                n for n, f in self.input_model.model_fields.items() if f.is_required()
            ]
            if len(required) != 1:
                raise TypeError(
                    f"{self.id} expects keyword arguments {list(self.input_model.model_fields)}"
                )
            kwargs = dict(self.input_model(**{required[0]: payload}))
        for name in self.ctx_params:
            kwargs[name] = ctx
        result = self.fn(**kwargs)
        if inspect.isawaitable(result):
            result = await result
        return result


class AgentRouter:
    """Composable component group attached via include_router (reference:
    sdk/python/agentfield/router.py:13 + agent.py:2042 — prefixing semantics)."""

    def __init__(self, prefix: str = ""):
        self.prefix = prefix.strip("_")
        self.components: list[ComponentDef] = []

    def reasoner(self, id: str | None = None, description: str = "", ai_defaults=None):
        return self._decorator("reasoner", id, description, ai_defaults)

    def skill(self, id: str | None = None, description: str = "", ai_defaults=None):
        return self._decorator("skill", id, description, ai_defaults)

    def _decorator(self, kind: str, id: str | None, description: str, ai_defaults=None):
        def deco(fn):
            cid = id or fn.__name__
            if self.prefix:
                cid = f"{self.prefix}_{cid}"
            self.components.append(
                ComponentDef(cid, kind, fn, description or (fn.__doc__ or ""),
                             ai_defaults=ai_defaults)
            )
            return fn

        return deco


class Agent:
    def __init__(
        self,
        node_id: str,
        control_plane: str = DEFAULT_CONTROL_PLANE,
        host: str = "127.0.0.1",
        port: int = 0,  # 0 → auto-assign (reference AGENTFIELD_AUTO_PORT)
        kind: str = "agent",
        heartbeat_interval: float = 2.0,  # reference enhanced-heartbeat cadence
        metadata: dict | None = None,
        ai_defaults: "AIConfig | dict | None" = None,  # agent-level ai()
        # defaults; per-reasoner ai_defaults= and explicit call arguments
        # override field-by-field (reference agent_ai.py:189-215)
        channel: bool = True,  # serve the persistent gateway↔node channel
        # (GET /channel, advertised via metadata.channel): the gateway
        # multiplexes executions over ONE WebSocket instead of a POST per
        # request, and token-streaming components (model nodes) stream
        # end-to-end. False → per-execution POSTs only, the pre-channel
        # wire behavior (docs/ARCHITECTURE.md data plane).
    ):
        if "." in node_id:
            raise ValueError("node_id must not contain '.'")
        self.ai_defaults = _norm_ai_defaults(ai_defaults, f"Agent {node_id!r}")
        self.node_id = node_id
        self.kind = kind
        self.host = host
        self.port = port
        self.metadata = metadata or {}
        self.channel_server = None
        if channel:
            from agentfield_tpu.control_plane.channel import ChannelServer

            self.channel_server = ChannelServer(invoke=self._channel_invoke)
            self.metadata.setdefault("channel", True)
        self.heartbeat_interval = heartbeat_interval
        self.client = ControlPlaneClient(control_plane)
        self.components: dict[str, ComponentDef] = {}
        self.mcp = None  # set via attach_mcp()
        self.extra_routes: list[tuple[str, str, Any]] = []  # (method, path, handler)
        self._runner: web.AppRunner | None = None
        self._hb_task: asyncio.Task | None = None
        self._pending: set[asyncio.Task] = set()
        self._reconnect_cbs: list[Any] = []

    # -- decorators -----------------------------------------------------

    def reasoner(self, id: str | None = None, description: str = "", ai_defaults=None):
        return self._decorator("reasoner", id, description, ai_defaults)

    def skill(self, id: str | None = None, description: str = "", ai_defaults=None):
        return self._decorator("skill", id, description, ai_defaults)

    def _decorator(self, kind: str, id: str | None, description: str, ai_defaults=None):
        def deco(fn):
            comp = ComponentDef(
                id or fn.__name__, kind, fn, description or (fn.__doc__ or ""),
                ai_defaults=ai_defaults,
            )
            self._add_component(comp)
            return fn

        return deco

    def _add_component(self, comp: ComponentDef) -> None:
        if comp.id in self.components:
            raise ValueError(f"duplicate component id {comp.id!r}")
        self.components[comp.id] = comp

    def include_router(self, router: AgentRouter) -> None:
        for comp in router.components:
            self._add_component(comp)

    def attach_mcp(self, manager) -> list[str]:
        """Register a started MCPManager's tools as skills and surface its
        health through /health."""
        self.mcp = manager
        return manager.attach_to_agent(self)

    # -- HTTP surface ---------------------------------------------------

    def _build_app(self) -> web.Application:
        app = web.Application()

        async def handle(req: web.Request) -> web.Response:
            comp = self.components.get(req.match_info["cid"])
            kind = "reasoner" if req.path.startswith("/reasoners/") else "skill"
            if comp is None or comp.kind != kind:
                return web.json_response({"error": "unknown component"}, status=404)
            try:
                body = await req.json() if req.can_read_body else {}
            except Exception:
                return web.json_response({"error": "invalid JSON"}, status=400)
            if not isinstance(body, dict):
                return web.json_response({"error": "JSON object body required"}, status=400)
            payload = body.get("input")
            ctx = ExecutionContext.from_headers(req.headers)
            if ctx is None:
                # Direct invocation (no gateway execution id): run inline.
                try:
                    result = await self._run(comp, payload, ExecutionContext.new_root())
                except pydantic.ValidationError as e:
                    return web.json_response({"error": str(e)}, status=422)
                except Exception as e:
                    return web.json_response({"error": repr(e)}, status=500)
                return web.json_response({"result": result})
            # Gateway-tracked: ack 202, execute in background, call back
            # (reference: agent.py:1182-1197 + _execute_async_with_callback).
            task = asyncio.create_task(self._run_tracked(comp, payload, ctx))
            self._pending.add(task)
            task.add_done_callback(self._pending.discard)
            return web.Response(status=202)

        async def health(_req):
            doc = {
                "status": "ok",
                "node_id": self.node_id,
                # control-plane link state (reference: ConnectionManager's
                # degraded-mode flag, connection_manager.py) — the agent
                # keeps serving locally even while the link is down
                "control_plane": self.connection_state,
            }
            if self.mcp is not None:
                doc["mcp"] = self.mcp.health()  # aggregated by the control
                # plane's HealthMonitor (reference: checkMCPHealthForNode)
            return web.json_response(doc)

        async def list_components(req: web.Request):
            kind = "reasoner" if req.path == "/reasoners" else "skill"
            return web.json_response(
                {
                    kind + "s": [
                        {"id": c.id, "description": c.description, "input_schema": c.input_schema}
                        for c in self.components.values()
                        if c.kind == kind
                    ]
                }
            )

        app.router.add_post("/reasoners/{cid}", handle)
        app.router.add_post("/skills/{cid}", handle)
        app.router.add_get("/health", health)
        app.router.add_get("/reasoners", list_components)
        app.router.add_get("/skills", list_components)
        if self.channel_server is not None:
            app.router.add_get("/channel", self.channel_server.handler)
        for method, path, handler in self.extra_routes:
            app.router.add_route(method, path, handler)
        return app

    def add_route(self, method: str, path: str, handler) -> None:
        """Attach a raw aiohttp route (e.g. the model node's token-stream
        endpoint). Must be called before start()."""
        self.extra_routes.append((method, path, handler))

    async def _channel_invoke(
        self, comp_id: str, payload: Any, headers: dict[str, str]
    ) -> Any:
        """Channel-submitted execution: same component dispatch as the POST
        handler, but the result rides back as a terminal frame instead of a
        status callback — one hop fewer, same DAG context propagation.
        Exceptions become terminal `failed` frames at the channel server
        (mirroring _run_tracked's repr(e) callbacks)."""
        comp = self.components.get(comp_id)
        if comp is None:
            raise LookupError(f"unknown component {comp_id!r}")
        ctx = ExecutionContext.from_headers(headers) or ExecutionContext.new_root()
        return await self._run(comp, payload, ctx)

    def channel_stream(self, comp_id: str, fn) -> None:
        """Register a token-streaming channel handler for a component (the
        model node registers `generate`); `fn(payload, headers, emit)`
        returns the final result after awaiting `emit(frame)` per token."""
        if self.channel_server is None:
            raise RuntimeError("channel disabled on this agent")
        self.channel_server.stream_handler(comp_id, fn)

    async def _run(self, comp: ComponentDef, payload: Any, ctx: ExecutionContext) -> Any:
        token = set_context(ctx)
        ctoken = _current_component.set(comp.id)
        try:
            return await comp.invoke(payload, ctx)
        finally:
            _current_component.reset(ctoken)
            reset_context(token)

    async def _run_tracked(self, comp: ComponentDef, payload: Any, ctx: ExecutionContext) -> None:
        try:
            result = await self._run(comp, payload, ctx)
            json.dumps(result)  # fail fast: an unserializable result must
            # surface as a failed execution, not a stranded-until-stale one
        except Exception as e:
            await self._safe_status(ctx.execution_id, "failed", error=repr(e))
        else:
            await self._safe_status(ctx.execution_id, "completed", result=result)

    async def _safe_status(self, execution_id: str, status: str, **kw) -> None:
        try:
            await self.client.post_status(execution_id, status, **kw)
        except Exception as e:
            # Control plane unreachable; the execution will be marked stale
            # by its cleanup — leave the operator a trace of the lost ack.
            log.debug(
                "status callback failed",
                execution_id=execution_id, status=status, error=repr(e),
            )

    # -- outbound: call() and ai() -------------------------------------

    def _outbound_ctx(self) -> ExecutionContext:
        ctx = current_context()
        return ctx.child() if ctx else ExecutionContext.new_root()

    async def call(self, target: str, _payload: Any = None, **kwargs) -> Any:
        """Cross-agent invocation through the gateway with DAG linkage
        (reference: Agent.call, agent.py:2472)."""
        payload = _payload if _payload is not None else (kwargs or None)
        doc = await self.client.execute(target, payload, headers=self._outbound_ctx().to_headers())
        if doc["status"] != "completed":
            raise RuntimeError(f"call {target} {doc['status']}: {doc.get('error')}")
        return doc["result"]

    async def _resolve_model_node(self, model: str | None) -> dict[str, Any]:
        return (await self._model_candidates(model))[0]

    async def _model_candidates(
        self, model: str | None, need: set[str] | None = None
    ) -> list[dict[str, Any]]:
        """Failover set: the named node alone, or every active model node in
        registration order (the reference's fallback chain iterates provider
        models, agent_ai.py:345-384 — here the units of failure are nodes).

        With `need` (required modalities, e.g. {"audio-out"}), nodes whose
        metadata advertises them come FIRST — in a mixed cluster a TTS/image
        request must not land on a node without the head. Nodes that
        advertise no modality list (older registrations) keep their place
        after advertising ones: unknown ≠ incapable, so failover still
        reaches them."""
        nodes = await self.client.list_nodes()
        if model is not None:
            for n in nodes:
                if n["node_id"] == model:
                    return [n]
            raise RuntimeError(f"model node {model!r} not registered")
        candidates = [n for n in nodes if n.get("kind") == "model" and n["status"] == "active"]
        if not candidates:
            raise RuntimeError("no active model node registered")
        if need:
            def rank(n: dict[str, Any]) -> int:
                mods = (n.get("metadata") or {}).get("modalities")
                if mods is None:
                    return 1  # unknown: after advertisers, before refusers
                return 0 if need.issubset(mods) else 2
            candidates.sort(key=rank)  # stable: registration order within rank
        return candidates

    _AI_BUILTIN = {
        "model": None, "max_new_tokens": 128, "temperature": 0.0,
        "top_k": 0, "top_p": 1.0, "stop_token_ids": None, "timeout": 600.0,
        "context_overflow": "truncate_left", "output": "text",
    }

    def _resolve_ai_params(self, explicit: dict[str, Any]) -> dict[str, Any]:
        """builtin < agent ai_defaults < executing reasoner's ai_defaults <
        explicit (non-None) call arguments — reference agent_ai.py:189-215."""
        merged = dict(self._AI_BUILTIN)
        if self.ai_defaults is not None:
            merged.update(self.ai_defaults.overrides())
        cid = _current_component.get()
        comp = self.components.get(cid) if cid else None
        if comp is not None and comp.ai_defaults is not None:
            merged.update(comp.ai_defaults.overrides())
        merged.update({k: v for k, v in explicit.items() if v is not None})
        if merged["stop_token_ids"] is not None:
            merged["stop_token_ids"] = list(merged["stop_token_ids"])
        return merged

    @staticmethod
    def _doc_node_down(doc: dict[str, Any]) -> bool:
        """Structured node-down detection, shared by every model-failover
        path (ai(), ai_embed): the transport layer records a synthesized
        ``status: node_down``, and a FAILED execution whose error names a
        gateway-level delivery failure (unreachable / vanished mid-call /
        5xx) means the node, not the request, is the problem — fail over.
        Deterministic request errors (bad pooling, empty input, schema
        violations) never match: replaying those cluster-wide is useless."""
        if doc.get("status") == "node_down":
            return True
        # dead_letter = the GATEWAY already retried node-level failures to
        # budget exhaustion on our behalf — by definition a node problem.
        if doc.get("status") == "dead_letter":
            return True
        if doc.get("status") != "failed":
            return False
        err = str(doc.get("error") or "")
        return (
            "agent call failed" in err
            or "vanished" in err
            or "agent returned 5" in err
        )

    async def ai(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        model: str | None = None,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        stop_token_ids: list[int] | None = None,
        timeout: float | None = None,
        schema: dict[str, Any] | None = None,
        context_overflow: str | None = None,
        images: list[Any] | None = None,
        audio: list[Any] | None = None,
        files: list[Any] | None = None,
        output: str | None = None,
        messages: list[dict[str, str]] | None = None,  # chat form
        # ([{role, content}]): the MODEL NODE applies its tokenizer's chat
        # template (reference CompleteWithMessages, sdk/go/ai/client.go:61).
        # Exclusive with prompt/tokens; media markers inside message content
        # still fuse.
        priority: int = 0,  # overload control (docs/FAULT_TOLERANCE.md):
        # rides the execute body through the gateway to the model node's
        # admission window — higher admits first under load, and a starved
        # higher-priority request may preempt a lower-priority slot.
        deadline_s: float | None = None,  # wall-clock budget from submit;
        # the gateway sheds the call (TIMEOUT) if it expires pre-dispatch
        # and forwards the REMAINING budget to the engine.
        n_branches: int = 1,  # test-time scaling (docs/PREFIX_CACHING.md
        # "Fork / COW branches"): the ENGINE forks the request's KV after
        # one prefill into this many branches, decodes them as batch-mates,
        # prunes per branch_policy, and returns only the winner — the
        # result gains a "branches" summary block. Text-only.
        branch_policy: Any = None,  # "best_of_n" (default) | "beam" | a
        # {"type", "verifier", "beam_width", "beam_interval"} object; a
        # "verifier" names a reasoner target the node dispatches candidate
        # texts to (through the gateway) instead of scoring by logprob sum.
        expect_followup: bool = False,  # agent-aware serving (docs/
        # OPERATIONS.md "Agent-aware serving"): declare that this session
        # will be hit again right after this call (a tool-call loop) — the
        # serving node pins the session's KV warm instead of racing its
        # TTL. The gateway also INFERS this for non-root steps of a
        # session-carrying chain; the explicit flag covers roots and
        # out-of-band callers. A latency hint only: results are identical.
        followup_candidates: list[str] | None = None,  # candidate next-step
        # texts (e.g. likely tool results rendered into the next prompt's
        # suffix) the node may speculatively prefill while the tool runs —
        # the real follow-up then pays TTFT only for what diverges.
        # Requires expect_followup; invalid entries are dropped, never
        # errors. Text-only.
        stream: bool = False,  # token streaming THROUGH the gateway: returns
        # an async iterator of frames instead of the result dict — token
        # frames from TTFT, then one {"terminal": True, "result": ...} frame.
        # Unlike ai_stream() (which bypasses the control plane and hits the
        # node directly), this path keeps gateway retry/failover, DAG
        # tracking, and the recorded execution row. Text-only.
    ) -> dict[str, Any]:
        """LLM call served by an in-tree TPU model node (replaces the
        reference's litellm path, agent_ai.py:95-447). Placement v0: first
        active model node (or `model` node id, used directly — the gateway
        validates it), with node-down failover across the remaining active
        model nodes.

        `context_overflow` defaults to "truncate_left" — over-long prompts
        keep their most recent tokens, mirroring the reference's token-aware
        trimming (agent_ai.py:262-325); pass "error" for a hard
        RequestTooLongError instead. A truncated call reports
        `truncated_prompt_tokens` in its result.

        With `schema` (a JSON schema), decoding is CONSTRAINED on the model
        node: the schema compiles to a token-level DFA whose mask makes
        invalid tokens unsampleable (serving/grammar.py), so the decoded text
        is schema-valid JSON by construction — no regex salvage (the
        reference's failure mode, agent_ai.py:424-447). The prompt still
        gains a strict-JSON instruction (steers content quality; correctness
        comes from the mask), and the result dict gains a "parsed" key.

        Parameters left at None resolve through the config hierarchy:
        agent-level ``Agent(ai_defaults=...)`` < the executing reasoner's
        ``@app.reasoner(ai_defaults=...)`` < explicit arguments here.

        ``files`` takes text-like attachments (paths, bytes, FileContent,
        or {"b64"/"path", "name", "mime"} dicts): their text inlines into
        the prompt as fenced blocks; binary files raise
        UnsupportedModalityError naming the supported routes."""
        p = self._resolve_ai_params({
            "model": model, "max_new_tokens": max_new_tokens,
            "temperature": temperature, "top_k": top_k, "top_p": top_p,
            "stop_token_ids": stop_token_ids, "timeout": timeout,
            "context_overflow": context_overflow, "output": output,
        })
        model = p["model"]
        max_new_tokens, temperature = p["max_new_tokens"], p["temperature"]
        top_k, top_p = p["top_k"], p["top_p"]
        stop_token_ids, timeout = p["stop_token_ids"], p["timeout"]
        context_overflow, output = p["context_overflow"], p["output"]
        if messages is not None:
            if prompt is not None or tokens is not None:
                raise ValueError("messages is exclusive with prompt/tokens")
            if not messages:
                raise ValueError("messages must be non-empty")
            messages = [dict(m) for m in messages]  # appends stay caller-invisible
        if n_branches != 1 and (schema is not None or images or audio or output != "text"):
            raise ValueError(
                "ai(n_branches=...) is text-only branch decoding; schema/"
                "media/output modes use an unbranched call"
            )
        if stream:
            if schema is not None or images or audio or files or output != "text":
                raise ValueError(
                    "ai(stream=True) is text-only token streaming; schema/"
                    "media/output modes use the unary ai() path"
                )
            return self._ai_stream_frames(
                prompt=prompt, tokens=tokens, messages=messages, model=model,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, stop_token_ids=stop_token_ids,
                timeout=timeout, priority=priority, deadline_s=deadline_s,
                n_branches=n_branches, branch_policy=branch_policy,
                expect_followup=expect_followup,
                followup_candidates=followup_candidates,
            )

        def _carrier_text() -> str | None:
            """The text the markers/instructions live in: the prompt, or the
            concatenated chat contents."""
            if messages is not None:
                return "\n".join(str(m.get("content", "")) for m in messages)
            return prompt

        def _carrier_append(text: str) -> None:
            """Append to the prompt, or to the LAST chat message's content
            (file blocks, missing media markers, the schema instruction)."""
            nonlocal prompt, messages
            if messages is not None:
                messages[-1]["content"] = str(messages[-1].get("content", "")) + text
            else:
                prompt = (prompt or "") + text

        if files:
            if tokens is not None:
                # _submit generates from `tokens` and ignores `prompt`; the
                # inlined file text would silently vanish (same contract as
                # the media-vs-tokens rejection on the model node)
                raise ValueError("files require a text 'prompt', not 'tokens'")
            from agentfield_tpu.sdk.multimodal import file_prompt_block

            blocks = [file_prompt_block(f) for f in _normalize_files(files)]
            if messages is None and prompt is None:
                prompt = "\n".join(blocks)
            else:
                _carrier_append("\n" + "\n".join(blocks))
        if images:
            if _carrier_text() is None:
                raise ValueError("images require a text prompt (or messages)")
            images = _normalize_images(images)
            # Each image needs an <image> marker in the prompt/chat; unmarked
            # images append at the end (reference: image parts are appended
            # in argument order, agent_ai.py:449).
            have = _carrier_text().count("<image>")
            missing = len(images) - have
            if missing < 0:
                raise ValueError(
                    f"prompt has {have} <image> markers "
                    f"but only {len(images)} images were passed"
                )
            if missing:
                _carrier_append("\n<image>" * missing)
        if audio:
            if _carrier_text() is None:
                raise ValueError("audio inputs require a text prompt (or messages)")
            audio = _normalize_audio(audio)
            have = _carrier_text().count("<audio>")
            missing = len(audio) - have
            if missing < 0:
                raise ValueError(
                    f"prompt has {have} <audio> markers "
                    f"but only {len(audio)} audio parts were passed"
                )
            if missing:
                _carrier_append("\n<audio>" * missing)
        if output not in ("text", "audio", "speech", "image"):
            raise ValueError(
                f"unknown output modality {output!r}: 'text' | 'audio' "
                "(speak the prompt, reference agent_ai.py:750 TTS) | "
                "'speech' (generate text, then speak it — chat-audio) | "
                "'image' (render the prompt, reference agent_ai.py:1004)"
            )
        if output != "text" and schema is not None:
            raise ValueError("schema-constrained decoding is text-only")
        if schema is not None:
            if _carrier_text() is None:
                raise ValueError("schema requires a text prompt (or messages)")
            from agentfield_tpu.sdk.structured import schema_instruction

            # the DFA mask on the node enforces correctness; this steers
            _carrier_append(schema_instruction(schema))
        ctx = current_context()
        payload = {
            "prompt": prompt,
            "tokens": tokens,
            "messages": messages,
            "images": images or None,
            "audios": audio or None,
            "output": output,
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "stop_token_ids": stop_token_ids or [],
            # Session affinity → model-node prefix-cache reuse across turns.
            "session_id": ctx.session_id if ctx else None,
            "response_schema": schema,
            "context_overflow": context_overflow,
        }
        if expect_followup:
            # Agent-aware serving: generate-input hints (the execute-body
            # flag below drives the gateway; these drive the model node).
            # Omitted entirely when unset — the generate schema's strict
            # bool rejects an explicit null.
            payload["expect_followup"] = True
            if followup_candidates:
                payload["followup_candidates"] = followup_candidates
        # Backpressure retry (the reference's rate limiter plays this role for
        # provider 429s — rate_limiter.py). Engine exhaustion reaches us two
        # ways: HTTP 503 (node inactive / async queue full) OR a FAILED
        # execution whose error names QueueFullError (the model node's
        # generate raised it and reported failure through the callback).
        # Node-down failures (unreachable / 5xx / vanished mid-call) fail over
        # to the next active model node — the reference's fallback-model chain
        # (agent_ai.py:345-384) re-designed for in-tree serving, where the
        # unit of failure is a node, not a provider model.
        need: set[str] = set()
        if images:
            need.add("image-in")
        if audio:
            need.add("audio-in")
        if output in ("audio", "speech"):
            need.add("audio-out")
        elif output == "image":
            need.add("image-out")
        candidates = await self._model_candidates(model, need=need or None)
        node_errors: list[str] = []
        doc: dict[str, Any] = {}
        for ci, cand in enumerate(candidates):
            node_id = cand["node_id"]
            attempts = 0
            while True:
                try:
                    # Fresh execution id per attempt: a failed/retried
                    # execution's id is already recorded, and replaying it
                    # would 409.
                    doc = await self.client.execute(
                        f"{node_id}.generate",
                        payload,
                        headers=self._outbound_ctx().to_headers(),
                        timeout=timeout,
                        priority=priority,
                        deadline_s=deadline_s,
                        n_branches=n_branches,
                        branch_policy=branch_policy,
                        expect_followup=expect_followup,
                    )
                except ControlPlaneError as e:
                    has_next = ci + 1 < len(candidates)
                    msg = str(e)
                    gone = any(
                        s in msg for s in ("is inactive", "is stopping", "is starting")
                    )
                    if e.status in (404, 410) or (e.status == 503 and gone):
                        # Node deregistered or marked inactive at the gateway
                        # — a down NODE, not backpressure: fail over now
                        # (retrying a dead node 5x first would defeat the
                        # failover this path exists for).
                        if has_next:
                            doc = {"status": "node_down", "error": str(e)}
                            break
                        raise
                    # 429 = transient overload with a Retry-After estimate;
                    # 503 = no capacity. Both are backpressure, not a dead
                    # node: retry here with patience.
                    if e.status not in (429, 503) or attempts >= 5:
                        if e.status in (429, 503) and has_next:
                            # persistent backpressure on this node: another
                            # candidate may have capacity
                            doc = {"status": "node_down", "error": str(e)}
                            break
                        raise
                    attempts += 1
                    await asyncio.sleep(_backpressure_delay(attempts, e.retry_after))
                    continue
                err = str(doc.get("error") or "")
                if (
                    # dead_letter: the gateway's own retries saw the same
                    # backpressure — still worth client-side patience
                    doc["status"] in ("failed", "dead_letter")
                    and ("QueueFullError" in err or "queue at capacity" in err)
                    and attempts < 5
                ):
                    attempts += 1
                    await asyncio.sleep(_backpressure_delay(attempts))
                    continue
                break
            if self._doc_node_down(doc) and ci + 1 < len(candidates):
                node_errors.append(f"{node_id}: {doc.get('error')}")
                continue
            break
        if doc.get("status") != "completed":
            detail = f"; failed over from {node_errors}" if node_errors else ""
            raise RuntimeError(f"ai() {doc.get('status')}: {doc.get('error')}{detail}")
        result = doc["result"]
        if schema is not None:
            from agentfield_tpu.sdk.structured import (
                StructuredOutputError,
                parse_structured,
            )

            if result.get("finish_reason") == "length":
                # The mask guarantees a valid *prefix*; only an EOS finish
                # guarantees a complete value.
                raise StructuredOutputError(
                    "constrained generation hit max_new_tokens before the "
                    "value completed — raise max_new_tokens (or bound the "
                    "schema, e.g. maxLength/maxItems)"
                )
            result["parsed"] = parse_structured(result.get("text", ""), schema)
        if isinstance(result, dict) and result.get("parts"):
            from agentfield_tpu.sdk.multimodal import detect_multimodal_response

            return detect_multimodal_response(result)
        return result

    async def _ai_stream_frames(
        self, *, prompt, tokens, messages, model, max_new_tokens, temperature,
        top_k, top_p, stop_token_ids, timeout, priority, deadline_s,
        n_branches=1, branch_policy=None, expect_followup=False,
        followup_candidates=None,
    ):
        """ai(stream=True) driver: token frames through the gateway's
        streaming execute, with node-down failover across model candidates
        — but ONLY while zero token frames have been yielded (a consumer
        that saw tokens must never see them twice; mid-stream loss surfaces
        as the gateway's dead-letter terminal instead)."""
        payload = {
            "prompt": prompt,
            "tokens": tokens,
            "messages": messages,
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "stop_token_ids": stop_token_ids or [],
            "session_id": (current_context().session_id if current_context() else None),
        }
        if expect_followup:
            payload["expect_followup"] = True
            if followup_candidates:
                payload["followup_candidates"] = followup_candidates
        candidates = await self._model_candidates(model)
        node_errors: list[str] = []
        for ci, cand in enumerate(candidates):
            node_id = cand["node_id"]
            yielded = False
            terminal: dict[str, Any] | None = None
            try:
                async for frame in self.client.execute_stream(
                    f"{node_id}.generate",
                    payload,
                    headers=self._outbound_ctx().to_headers(),
                    timeout=timeout,
                    priority=priority,
                    deadline_s=deadline_s,
                    n_branches=n_branches,
                    branch_policy=branch_policy,
                    expect_followup=expect_followup,
                ):
                    kind = frame.get("kind")
                    if kind == "token":
                        yielded = True
                        yield {
                            "token": frame.get("token"),
                            "index": frame.get("index"),
                            "finished": bool(frame.get("finished")),
                            "finish_reason": frame.get("finish_reason"),
                            "text": frame.get("text"),
                            "logprob": frame.get("logprob"),
                        }
                    elif kind in ("terminal", "dropped"):
                        terminal = frame
                        break
            except (ControlPlaneError, aiohttp.ClientError) as e:
                if yielded or ci + 1 >= len(candidates):
                    raise
                node_errors.append(f"{node_id}: {e}")
                continue
            if terminal is None or terminal.get("kind") == "dropped":
                raise RuntimeError(
                    "stream ended without a terminal frame "
                    f"({(terminal or {}).get('error') or 'connection dropped'})"
                )
            if terminal.get("status") == "completed":
                yield {
                    "terminal": True,
                    "finished": True,
                    "status": "completed",
                    "result": terminal.get("result"),
                    "execution_id": terminal.get("execution_id"),
                }
                return
            doc = {"status": terminal.get("status"), "error": terminal.get("error")}
            if not yielded and self._doc_node_down(doc) and ci + 1 < len(candidates):
                node_errors.append(f"{node_id}: {doc.get('error')}")
                continue
            detail = f"; failed over from {node_errors}" if node_errors else ""
            raise RuntimeError(
                f"ai(stream=True) {doc.get('status')}: {doc.get('error')}{detail}"
            )

    async def ai_with_vision(self, prompt: str, image: Any, **kw) -> dict[str, Any]:
        """Image-understanding sugar (reference: ai_with_vision,
        agent_ai.py:1004 — there image *generation* via providers; here the
        served direction is image INPUT through the model node's vision
        tower)."""
        return await self.ai(prompt, images=[image], **kw)

    async def ai_with_multimodal(self, *parts: Any, **kw) -> dict[str, Any]:
        """Mixed-content call (reference: ai_with_multimodal,
        agent_ai.py:1069): args classify in order — text joins the prompt,
        images ride to the vision tower, audio to the audio tower."""
        from agentfield_tpu.sdk.multimodal import split_prompt_and_media

        prompt, images, audios = split_prompt_and_media(list(parts))
        return await self.ai(
            prompt, images=images or None, audio=audios or None, **kw
        )

    async def generate_image(self, prompt: str, **kw) -> dict[str, Any]:
        """Text-to-image sugar (reference: generate_image, agent_ai.py:1004
        forwards to provider image APIs; here the node's in-tree image head
        renders). Returns a MultimodalResponse whose first part is a PNG."""
        kw.setdefault("output", "image")
        return await self.ai(prompt, **kw)

    async def ai_with_audio(
        self, prompt: str, audio: Any = None, **kw
    ) -> dict[str, Any]:
        """Audio sugar (reference: ai_with_audio, agent_ai.py:750). With an
        ``audio`` input the clip is understood through the node's audio tower
        (``<audio>`` early fusion); without one the call is TTS — the node's
        TTS head speaks the generated text (output='speech')."""
        if audio is not None:
            return await self.ai(prompt, audio=[audio], **kw)
        kw.setdefault("output", "speech")
        return await self.ai(prompt, **kw)

    async def ai_embed(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        model: str | None = None,
        pooling: str = "mean",
        context_overflow: str = "error",
        timeout: float = 600.0,
    ) -> dict[str, Any]:
        """Text → L2-normalized embedding from a model node's LM hidden
        states. The reference cannot embed in-cluster (its memory vector API
        expects provider-produced vectors); here
        ``vector_set(key, (await ai_embed(text))["embedding"])`` →
        ``vector_search`` closes the loop with no external API.

        Failover applies only to TRANSPORT/node-down failures — a
        deterministic request error (bad pooling, empty input) raises
        immediately instead of replaying the doomed request cluster-wide.
        Caveat: vectors from DIFFERENT models are different embedding
        spaces; pin ``model`` (or Agent ai_defaults) when more than one
        model node serves, and never mix models within one vector scope
        (the result's "model" field is there to check)."""
        model = self._resolve_ai_params({"model": model})["model"]
        candidates = await self._model_candidates(model, need=None)
        errors: list[str] = []
        doc: dict[str, Any] = {}
        for ci, cand in enumerate(candidates):
            node_id = cand["node_id"]
            try:
                doc = await self.client.execute(
                    f"{node_id}.embed",
                    {"prompt": prompt, "tokens": tokens, "pooling": pooling,
                     "context_overflow": context_overflow},
                    headers=self._outbound_ctx().to_headers(),
                    timeout=timeout,
                )
            except ControlPlaneError as e:
                if ci + 1 < len(candidates):
                    errors.append(f"{node_id}: {e}")
                    continue
                raise
            if doc.get("status") == "completed":
                return doc["result"]
            if self._doc_node_down(doc) and ci + 1 < len(candidates):
                errors.append(f"{node_id}: {doc.get('error')}")
                continue
            break  # deterministic failure: do not replay cluster-wide
        detail = f"; failed over from {errors}" if errors else ""
        raise RuntimeError(
            f"ai_embed {doc.get('status')}: {doc.get('error')}{detail}"
        )

    async def ai_stream(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        model: str | None = None,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        stop_token_ids: list[int] | None = None,
        timeout: float | None = None,
        messages: list[dict[str, str]] | None = None,  # chat form (the node
        # applies its tokenizer's chat template, as in ai())
    ):
        """Token-streaming LLM call: SSE straight from the model node (data
        plane), with DAG visibility via workflow lifecycle events. Yields
        {"token", "index", "finished", "finish_reason", "text"?} frames.

        Early exit: a consumer that `break`s out is recorded as a *completed*
        execution with finish_reason "client_stopped". Note that generator
        finalization after `break` is deferred to GC unless you iterate under
        ``contextlib.aclosing(...)`` — use that for deterministic DAG events."""
        import aiohttp

        # same defaults hierarchy as ai(): agent < reasoner < explicit args
        rp = self._resolve_ai_params({
            "model": model, "max_new_tokens": max_new_tokens,
            "temperature": temperature, "top_k": top_k, "top_p": top_p,
            "stop_token_ids": stop_token_ids, "timeout": timeout,
        })
        model = rp["model"]
        max_new_tokens, temperature = rp["max_new_tokens"], rp["temperature"]
        top_k, top_p = rp["top_k"], rp["top_p"]
        stop_token_ids, timeout = rp["stop_token_ids"], rp["timeout"]
        if messages is not None:
            if prompt is not None or tokens is not None:
                raise ValueError("messages is exclusive with prompt/tokens")
            if not messages:
                raise ValueError("messages must be non-empty")
        node = await self._resolve_model_node(model)
        ctx = self._outbound_ctx()
        base = {
            "event": "start",
            "execution_id": ctx.execution_id,
            "run_id": ctx.run_id,
            "parent_execution_id": ctx.parent_execution_id,
            "target": f"{node['node_id']}.generate",
            "input": {
                "prompt": prompt, "messages": messages,
                "max_new_tokens": max_new_tokens, "stream": True,
            },
        }
        try:
            await self.client.post_workflow_event(base)
        except Exception as e:
            # tracking is best-effort; the stream itself must not fail
            log.debug("workflow start event failed", error=repr(e))
        payload = {
            "prompt": prompt,
            "tokens": tokens,
            "messages": messages,
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "stop_token_ids": stop_token_ids or [],
            "session_id": ctx.session_id,
        }
        collected: list[int] = []
        finish_reason = None
        failed = False
        try:
            async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=timeout)
            ) as s:
                async with s.post(
                    f"{node['base_url'].rstrip('/')}/generate/stream", json=payload
                ) as resp:
                    if resp.status != 200:
                        failed = True
                        err = (await resp.text())[:300]
                        raise RuntimeError(f"stream failed [{resp.status}]: {err}")
                    async for line in resp.content:
                        if not line.startswith(b"data: "):
                            continue
                        frame = json.loads(line[6:])
                        collected.append(frame["token"])
                        finish_reason = frame.get("finish_reason")
                        yield frame
                        if frame.get("finished"):
                            break
        except BaseException:
            failed = failed or finish_reason is None and collected == []
            raise
        finally:
            # A consumer break is a legitimate completion ("client_stopped");
            # only genuine transport/model failures record an error event.
            done = dict(base)
            if failed:
                done["event"] = "error"
                done["error"] = "stream aborted"
            else:
                done["event"] = "complete"
            done["result"] = {
                "tokens": collected,
                "finish_reason": finish_reason or "client_stopped",
            }
            try:
                await self.client.post_workflow_event(done)
            except Exception as e:
                # best-effort tracking, same contract as the start event
                log.debug("workflow done event failed", error=repr(e))

    async def handle_serverless(self, event: dict[str, Any]) -> dict[str, Any]:
        """Process one invocation without a long-lived HTTP server (reference:
        Agent.handle_serverless, agent.py:566 — the Lambda-style entrypoint;
        the control plane registers such nodes with kind='serverless' and the
        platform's URL as base_url). Event shape:
        {"component": "<id>", "input": ..., "headers": {X-* context}}."""
        comp = self.components.get(event.get("component", ""))
        if comp is None:
            return {"status": "failed", "error": f"unknown component {event.get('component')!r}"}
        ctx = ExecutionContext.from_headers(event.get("headers", {})) or ExecutionContext.new_root()
        try:
            result = await self._run(comp, event.get("input"), ctx)
            json.dumps(result)
        except Exception as e:
            return {"status": "failed", "error": repr(e), "execution_id": ctx.execution_id}
        return {"status": "completed", "result": result, "execution_id": ctx.execution_id}

    async def note(self, note: Any, actor: str | None = None) -> None:
        """Attach a note to the current execution (reference: Agent.note,
        agent.py:2804 → execution notes API). No-op outside an execution."""
        ctx = current_context()
        if ctx is None:
            return
        try:
            await self.client.add_note(ctx.execution_id, note, actor or self.node_id)
        except Exception as e:
            # notes are advisory; never fail the reasoner over one
            log.debug(
                "note delivery failed",
                execution_id=ctx.execution_id, error=repr(e),
            )

    # -- memory façade --------------------------------------------------

    @property
    def memory(self) -> ControlPlaneClient:
        """Scoped memory API (reference: Agent.memory, agent.py:750)."""
        return self.client

    # -- lifecycle ------------------------------------------------------

    def _callback_candidates(self) -> list[str]:
        """Candidate callback URLs in preference order, mirroring the
        reference's container-IP cooperation (sdk agent.py:66-303 detects
        candidates; the control plane probes them, nodes.go:205-276):
        explicit env override > bound host > detected outbound IP >
        hostname > loopback."""
        import os
        import socket

        out: list[str] = []

        def add(url: str | None) -> None:
            if url and url not in out:
                out.append(url)

        add(os.environ.get("AGENT_CALLBACK_URL"))
        if self.host not in ("0.0.0.0", "::", ""):
            add(f"http://{self.host}:{self.port}")
        try:
            # UDP connect never sends a packet; it just resolves the route,
            # yielding the address a remote control plane could reach us on.
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect(("10.255.255.255", 1))
                add(f"http://{s.getsockname()[0]}:{self.port}")
        except OSError:
            pass
        try:
            add(f"http://{socket.gethostbyname(socket.gethostname())}:{self.port}")
        except OSError:
            pass
        add(f"http://127.0.0.1:{self.port}")
        return out

    def _node_spec(self) -> dict[str, Any]:
        return {
            "node_id": self.node_id,
            "base_url": f"http://{self.host}:{self.port}",
            "callback_candidates": self._callback_candidates(),
            "kind": self.kind,
            "metadata": self.metadata,
            "reasoners": [
                {"id": c.id, "description": c.description, "input_schema": c.input_schema}
                for c in self.components.values()
                if c.kind == "reasoner"
            ],
            "skills": [
                {"id": c.id, "description": c.description, "input_schema": c.input_schema}
                for c in self.components.values()
                if c.kind == "skill"
            ],
        }

    async def start(self) -> None:
        """Start the HTTP server, register, begin heartbeating."""
        self._runner = web.AppRunner(self._build_app())
        await self._runner.setup()
        # Bind port 0 directly and read back the kernel-assigned port — no
        # probe-close-rebind TOCTOU race.
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        await self.client.register_node(self._node_spec())
        self._hb_task = asyncio.create_task(self._heartbeat_loop())

    async def stop(self) -> None:
        if self._hb_task:
            self._hb_task.cancel()
            await asyncio.gather(self._hb_task, return_exceptions=True)
        if self._pending:
            await asyncio.gather(*self._pending, return_exceptions=True)
        try:
            await self.client.heartbeat(self.node_id, status="stopping")
        # afcheck: ignore[except-swallow] shutdown courtesy beat; the plane may already be gone and the lease sweep covers us
        except Exception:
            pass
        if self.channel_server is not None:
            await self.channel_server.close()
        if self._runner:
            await self._runner.cleanup()
        await self.client.close()

    # Optional provider of live stats shipped with each heartbeat (model
    # nodes set this to their engine counters).
    heartbeat_stats: Any = None  # callable -> dict | None
    # Link-state machine (reference: ConnectionManager, connection_manager.py
    # :197 — background reconnect loop + degraded-mode flag): "connected" |
    # "degraded" (heartbeats failing, local serving continues) — transitions
    # are driven by the heartbeat loop; on_reconnect callbacks fire when the
    # link heals after a degraded stretch.
    connection_state: str = "connected"
    _DEGRADED_AFTER = 3  # consecutive heartbeat failures

    def on_reconnect(self, cb) -> None:
        """Register a callback (sync or async, no args) fired after the
        control-plane link recovers from a degraded stretch."""
        self._reconnect_cbs.append(cb)

    def _fire_reconnect(self) -> None:
        """Run observers off the heartbeat loop — a slow callback must never
        stall heartbeating (the node would flap dead again immediately)."""

        async def run() -> None:
            for cb in self._reconnect_cbs:
                try:
                    r = cb()
                    if inspect.isawaitable(r):
                        await r
                except Exception as e:
                    # observer errors must not break heartbeating
                    log.debug(
                        "reconnect observer failed",
                        observer=getattr(cb, "__name__", repr(cb)),
                        error=repr(e),
                    )

        task = asyncio.create_task(run())
        self._pending.add(task)
        task.add_done_callback(self._pending.discard)

    async def _heartbeat_loop(self) -> None:
        failures = 0
        while True:
            await asyncio.sleep(self.heartbeat_interval)
            # A broken stats provider must degrade to a stats-less heartbeat,
            # never suppress the heartbeat itself (the node would be marked
            # dead while perfectly healthy).
            stats = None
            try:
                if callable(self.heartbeat_stats):
                    stats = self.heartbeat_stats()
            except Exception as e:
                log.debug("heartbeat stats provider failed", error=repr(e))
            try:
                await self.client.heartbeat(self.node_id, stats=stats)
            except ControlPlaneError as e:
                failures += 1
                if e.status == 404:  # control plane restarted: re-register
                    try:
                        await self.client.register_node(self._node_spec())
                    except Exception as re_err:
                        # next heartbeat retries; keep the failure visible
                        log.debug(
                            "re-registration after 404 failed",
                            node_id=self.node_id, error=repr(re_err),
                        )
                    else:
                        # The node is live on the fresh plane NOW — that is
                        # the recovery, not the next heartbeat. A 404 proves
                        # the plane lost our registration (restart), so
                        # observers resync even if we never went degraded
                        # (fast restart between two heartbeats).
                        failures = 0
                        self.connection_state = "connected"
                        self._fire_reconnect()
            except Exception:
                failures += 1  # transient; keep heartbeating
            else:
                if self.connection_state == "degraded":
                    self.connection_state = "connected"
                    # a proxy blip heals silently (no 404) — observers still
                    # hear about the recovery
                    self._fire_reconnect()
                failures = 0
            if failures >= self._DEGRADED_AFTER:
                self.connection_state = "degraded"

    def serve(self) -> None:
        """Blocking entrypoint for standalone agent processes. Registration
        retries with backoff — a control plane that is still booting (or
        briefly down) must not kill the agent (reference: ConnectionManager
        retry loop, connection_manager.py:197)."""

        import aiohttp

        requested_port = self.port  # 0 → re-draw a fresh port on every retry

        async def main():
            delay = 1.0
            while True:
                try:
                    await self.start()
                    break
                except (ControlPlaneError, aiohttp.ClientError, ConnectionError, OSError) as e:
                    # Retry only genuinely transient conditions. A 4xx from
                    # registration is a config error; EADDRINUSE on a FIXED
                    # port won't heal (port 0 re-draws, so that retries fine).
                    # Order matters: aiohttp.ClientError (network to the
                    # control plane, incl. ClientConnectorError which IS an
                    # OSError but not a ConnectionError) must retry.
                    if isinstance(e, ControlPlaneError) and e.status < 500:
                        raise
                    if (
                        not isinstance(e, (aiohttp.ClientError, ConnectionError))
                        and isinstance(e, OSError)
                        and requested_port != 0
                    ):
                        raise
                    print(
                        f"[agentfield] {self.node_id}: control plane not ready "
                        f"({e!r}); retrying in {delay:.0f}s",
                        flush=True,
                    )
                    if self._runner:  # unbind before retrying start()
                        await self._runner.cleanup()
                        self._runner = None
                    self.port = requested_port
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 30.0)
            print(
                f"[agentfield] {self.node_id} serving on {self.host}:{self.port} "
                f"({len(self.components)} components), control plane {self.client.base_url}",
                flush=True,
            )
            stop = asyncio.Event()
            try:
                await stop.wait()
            finally:
                await self.stop()

        asyncio.run(main())


def _normalize_images(items: list[Any]) -> list[dict[str, Any]]:
    """ai(images=...) accepts ImageContent, raw bytes, file paths, pre-built
    {"b64": ...} wire dicts, or pixel arrays; everything normalizes to the
    model node's wire forms (base64 blob or nested array)."""
    import base64 as _b64
    from pathlib import Path as _Path

    from agentfield_tpu.sdk.multimodal import ImageContent, classify

    out: list[dict[str, Any]] = []
    for item in items:
        if isinstance(item, dict) and "b64" in item:
            out.append(item)
            continue
        if isinstance(item, (str, _Path)):
            item = ImageContent.from_file(item)
        elif isinstance(item, bytes):
            item = classify(item)
        if isinstance(item, ImageContent):
            out.append({"b64": _b64.b64encode(item.data).decode()})
        elif isinstance(item, (list, tuple)) or hasattr(item, "__array__"):
            import numpy as _np

            # tolist() all the way down: a shallow list() of a 3-D ndarray
            # would put ndarrays inside the JSON payload
            out.append(_np.asarray(item).tolist())
        else:
            raise TypeError(f"cannot use {type(item).__name__} as an image input")
    return out


def _normalize_files(items: list[Any]) -> list[Any]:
    """ai(files=...) accepts FileContent, file paths, raw bytes, or
    {"b64"/"path", "name", "mime"} dicts — everything normalizes to
    FileContent for prompt inlining. Image/audio bytes are redirected with
    a pointed error (they have dedicated tower routes)."""
    import base64 as _b64
    from pathlib import Path as _Path

    from agentfield_tpu.sdk.multimodal import (
        AudioContent,
        FileContent,
        ImageContent,
        classify,
    )

    out: list[Any] = []
    for item in items:
        if isinstance(item, dict):
            if "b64" in item:
                data = _b64.b64decode(item["b64"])
                name = item.get("name", "blob")
                if "mime" in item:
                    item = FileContent(data, name=name, mime=item["mime"])
                else:
                    # sniff magic like the raw-bytes path, so b64-wrapped
                    # media gets the pointed images=/audio= redirect below
                    sniffed = classify(data)
                    item = (
                        FileContent(data, name=name, mime=sniffed.mime)
                        if isinstance(sniffed, FileContent)
                        else sniffed
                    )
            elif "path" in item:
                item = FileContent.from_file(item["path"])
            else:
                raise TypeError("file dicts need 'b64' or 'path'")
        elif isinstance(item, (str, _Path)):
            item = FileContent.from_file(item)
        elif isinstance(item, bytes):
            item = classify(item)  # sniffs magic: may be image/audio bytes
        if isinstance(item, (ImageContent, AudioContent)):
            kind = "images=" if isinstance(item, ImageContent) else "audio="
            raise TypeError(
                f"this looks like {'an image' if kind == 'images=' else 'audio'} — "
                f"pass it via {kind} (it routes to the model node's tower)"
            )
        if not isinstance(item, FileContent):
            raise TypeError(f"cannot use {type(item).__name__} as a file input")
        out.append(item)
    return out


def _normalize_audio(items: list[Any]) -> list[dict[str, Any]]:
    """ai(audio=...) accepts AudioContent, raw WAV bytes, file paths,
    pre-built {"b64": ...} wire dicts, or sample arrays; everything
    normalizes to the model node's wire forms (base64 WAV or sample list)."""
    import base64 as _b64
    from pathlib import Path as _Path

    from agentfield_tpu.sdk.multimodal import AudioContent, classify

    out: list[dict[str, Any]] = []
    for item in items:
        if isinstance(item, dict) and "b64" in item:
            out.append(item)
            continue
        if isinstance(item, (str, _Path)):
            item = AudioContent.from_file(item)
        elif isinstance(item, bytes):
            item = classify(item)
        if isinstance(item, AudioContent):
            out.append({"b64": _b64.b64encode(item.data).decode()})
        elif isinstance(item, (list, tuple)) or hasattr(item, "__array__"):
            import numpy as _np

            out.append(_np.asarray(item, _np.float32).reshape(-1).tolist())
        else:
            raise TypeError(f"cannot use {type(item).__name__} as an audio input")
    return out
