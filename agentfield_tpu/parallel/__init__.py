from agentfield_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_SEQ,
    AXIS_STAGE,
    auto_mesh_shape,
    make_mesh,
    use_mesh,
)
from agentfield_tpu.parallel.sharding import (  # noqa: F401
    named_sharding,
    param_pspecs,
    shard_params,
)
