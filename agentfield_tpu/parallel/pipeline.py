"""Pipeline parallelism: GPipe-style stage execution over the `stage` axis.

The transformer's stacked layers split into contiguous stage chunks, each
resident on one ring position of the ``stage`` mesh axis (DCN-friendly:
activations cross stages once per microbatch tick, weights never move).
Microbatches flow through a ``lax.fori_loop`` of clock ticks; activations hop
stages with ``ppermute``. Autodiff works through the collective (its
transpose is the reverse permute), so the same function serves training —
bubble-optimal schedules (1F1B) are a later optimization, correctness and
memory locality come first (SURVEY §2.4 PP row: stage-sharded layer-scan
across pods).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models import llama
from agentfield_tpu.parallel.mesh import AXIS_STAGE, to_varying
from agentfield_tpu.parallel.mesh import shard_map as shard_map_compat


def split_layers_for_stages(params, num_stages: int):
    """Reshape stacked layer leaves [L, ...] → [num_stages, L/num_stages, ...]
    (the leading stage axis is what shards over `stage`)."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible into {num_stages} stages")
    return jax.tree.map(
        lambda p: p.reshape(num_stages, L // num_stages, *p.shape[1:]), params["layers"]
    )


def _stage_body(cfg: LlamaConfig, stage_layers, x, positions):
    """Run this device's chunk of layers over one microbatch activation."""

    def body(x, lp):
        h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        cos, sin = llama.rope_sincos(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        q, k, v = llama.qkv_proj(lp, h, cfg, cos, sin)
        attn = llama.attention_ref(
            q, k, v, positions, positions, jnp.ones_like(positions, dtype=bool),
            window=cfg.sliding_window,
        )
        x = x + (attn.reshape(*attn.shape[:2], -1) @ lp["wo"]).astype(x.dtype)
        x = x + llama.mlp_block(lp, x, cfg)
        return x, None

    x, _ = jax.lax.scan(body, x, stage_layers)
    return x


def _pipeline_local(stage_layers, x_micro, positions, cfg: LlamaConfig, axis: str):
    """Per-device body under shard_map. x_micro: [M, Bm, S, D] microbatches
    (replicated); stage_layers: this device's [L/S, ...] chunk."""
    n_stages = jax.lax.psum(1, axis)
    my_stage = jax.lax.axis_index(axis)
    M, Bm, S, D = x_micro.shape
    ticks = M + n_stages - 1

    def tick(t, carry):
        buf, outputs = carry
        # Stage 0 injects microbatch t (when in range); others take the buffer
        # that arrived from the previous stage last tick.
        m_in = jnp.where(t < M, t, 0)
        inject = x_micro[m_in]
        x_in = jnp.where(my_stage == 0, inject, buf)
        active = (t - my_stage >= 0) & (t - my_stage < M)
        y = _stage_body(cfg, stage_layers, x_in, positions)
        y = jnp.where(active, y, x_in)  # idle ticks pass zeros along harmlessly
        # Last stage emits microbatch (t - n_stages + 1) at this tick.
        m_out = t - (n_stages - 1)
        emit = (my_stage == n_stages - 1) & (m_out >= 0)
        outputs = jax.lax.cond(
            emit,
            lambda o: jax.lax.dynamic_update_index_in_dim(o, y, jnp.maximum(m_out, 0), 0),
            lambda o: o,
            outputs,
        )
        # Rotate activations one stage forward (ring; last→first carries junk
        # that stage 0 ignores because it always injects).
        nxt = jax.lax.ppermute(
            y, axis, [(s, (s + 1) % n_stages) for s in range(n_stages)]
        )
        return nxt, outputs

    buf0 = to_varying(jnp.zeros((Bm, S, D), x_micro.dtype), axis)
    out0 = to_varying(jnp.zeros_like(x_micro), axis)
    _, outputs = jax.lax.fori_loop(0, ticks, tick, (buf0, out0))
    # Only the last stage holds real outputs; zero-mask + psum broadcasts them
    # to every ring position (out_specs replicate over stage).
    is_last = (my_stage == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * is_last, axis)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "num_microbatches"))
def pipeline_forward(
    params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S]
    positions: jax.Array,  # [B, S]
    mesh: Mesh,
    num_microbatches: int = 2,
):
    """Full forward with the layer stack pipelined over `stage`. Embedding and
    unembedding run replicated (they are small next to the stack). Returns
    logits [B, S, V] identical to the dense forward."""
    n_stages = mesh.shape[AXIS_STAGE]
    B = tokens.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
    stage_layers = split_layers_for_stages(params, n_stages)

    x = llama.embed_tokens(params, cfg, tokens)  # [B, S, D]
    Bm = B // num_microbatches
    x_micro = x.reshape(num_microbatches, Bm, *x.shape[1:])
    pos_m = positions[:Bm]  # positions identical across microbatches by construction

    fn = shard_map_compat(
        functools.partial(_pipeline_local, cfg=cfg, axis=AXIS_STAGE),
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(AXIS_STAGE), stage_layers),
            P(),
            P(),
        ),
        out_specs=P(),
    )
    y = fn(stage_layers, x_micro, pos_m)
    y = y.reshape(B, *y.shape[2:])
    return llama.unembed(params, cfg, y)