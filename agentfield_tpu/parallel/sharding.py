"""GSPMD sharding rules for Llama parameter pytrees.

Megatron-style tensor parallelism expressed as PartitionSpecs: qkv and
gate/up projections are column-parallel (output dim on ``model``), o_proj and
down_proj are row-parallel (input dim on ``model``), embeddings shard the
vocab. XLA inserts the psum/all-gathers over ICI — there is no hand-written
collective in the model code.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.parallel.mesh import AXIS_MODEL


def param_pspecs(cfg: LlamaConfig) -> dict[str, Any]:
    """PartitionSpec pytree matching ``models.llama.init_params``.
    Layer leaves have a leading stacked-layer axis (never sharded — it is
    scanned over; pipeline parallelism splits it explicitly instead)."""
    m = AXIS_MODEL
    if cfg.num_experts > 0:
        from agentfield_tpu.parallel.mesh import AXIS_EXPERT as ex

        # Mixtral MoE FFN: experts shard over `expert` (EP), the ffn dim over
        # `model` (TP) — both axes exist (size 1 when unused) on every
        # make_mesh mesh, so EP×TP and TP-only meshes share these specs.
        mlp_specs: dict[str, Any] = {
            "router": P(None, None, None),
            "w_gate": P(None, ex, None, m),
            "w_up": P(None, ex, None, m),
            "w_down": P(None, ex, m, None),
        }
    else:
        mlp_specs = {
            "w_gate": P(None, None, m),
            "w_up": P(None, None, m),
            "w_down": P(None, m, None),
        }
    specs: dict[str, Any] = {
        "embed": P(m, None),  # vocab-sharded; doubles as column-parallel tied lm_head
        "layers": {
            "attn_norm": P(None, None),
            "mlp_norm": P(None, None),
            "wq": P(None, None, m),
            "wk": P(None, None, m),
            "wv": P(None, None, m),
            "wo": P(None, m, None),
            **mlp_specs,
        },
        "final_norm": P(None),
    }
    if cfg.attn_bias:
        specs["layers"]["bq"] = P(None, m)
        specs["layers"]["bk"] = P(None, m)
        specs["layers"]["bv"] = P(None, m)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, m)
    return specs


def named_sharding(mesh: Mesh, spec_tree: Any) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def _quant_aware(specs: Any, params: Any) -> Any:
    """Expand weight specs to match int8-quantized leaves: the QuantW node
    carries (q [L, in, out], scale [L, out]) — q takes the full spec, scale
    keeps the (layer, output) axes (the output axis is what TP shards)."""
    from agentfield_tpu.models.quant import QuantW

    def fix(spec, p):
        if isinstance(p, QuantW):
            # scale = q minus the contraction (-2) axis: [L, out] for dense
            # stacks, [L, E, out] for MoE expert stacks
            return QuantW(spec, P(*spec[:-2], spec[-1]))
        return spec

    return jax.tree.map(
        fix, specs, params, is_leaf=lambda x: isinstance(x, P)
    )


def shard_params(params: Any, cfg: LlamaConfig, mesh: Mesh) -> Any:
    """Place an (unsharded) param pytree onto the mesh. One pytree-aware
    device_put so XLA batches the host-to-device transfers."""
    specs = _quant_aware(param_pspecs(cfg), params)
    return jax.device_put(params, named_sharding(mesh, specs))


def check_divisibility(cfg: LlamaConfig, tp: int, paged_kv: bool = False) -> None:
    """TP degree must divide every model-sharded dimension. The GSPMD forward
    only needs the flattened projection dims; the serving engine's paged KV
    cache additionally shards the *head* axes, so it requires head-count
    divisibility too (`paged_kv=True`)."""
    dims = [
        ("q_dim", cfg.q_dim),
        ("kv_dim", cfg.kv_dim),
        ("intermediate_size", cfg.intermediate_size),
        ("vocab_size", cfg.vocab_size),
    ]
    if paged_kv:
        dims += [("num_heads", cfg.num_heads), ("num_kv_heads", cfg.num_kv_heads)]
    for name, dim in dims:
        if dim % tp:
            raise ValueError(f"tp={tp} does not divide {name}={dim} for this config")
