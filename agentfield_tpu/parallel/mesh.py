"""Device meshes for the TPU build.

The reference's only distribution axes are HTTP-coordinated agent processes
and a Go worker pool (reference: internal/handlers/execute.go:1341-1386, SURVEY
§2.4) — tensor math happened in external providers. Here the compute scales
over a ``jax.sharding.Mesh``: XLA inserts ICI/DCN collectives from sharding
annotations; the control plane never touches tensor traffic.

Canonical axis names (used by every PartitionSpec in the repo):

- ``data``    — batch/data parallelism (DP)
- ``model``   — tensor parallelism over heads / ffn dims (TP, rides ICI)
- ``seq``     — sequence/context parallelism (SP/CP, ring attention)
- ``expert``  — expert parallelism for MoE layers (EP)
- ``stage``   — pipeline stages across slices (PP, rides DCN)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"

# Mesh axis order: slower-varying axes first so that `model` (the most
# bandwidth-hungry axis) maps to physically adjacent devices on the ICI torus.
CANONICAL_ORDER = (AXIS_STAGE, AXIS_DATA, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)


def make_mesh(shape: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh from an {axis: size} dict. Axes are laid out in
    CANONICAL_ORDER; missing axes get size 1 (so PartitionSpecs referring to
    any canonical axis always resolve)."""
    if devices is None:
        devices = jax.devices()
    shape = dict(shape or {})
    n = int(np.prod(list(shape.values()))) if shape else len(devices)
    if not shape:
        shape = {AXIS_DATA: n}
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    full = [(ax, shape.get(ax, 1)) for ax in CANONICAL_ORDER]
    dims = [s for _, s in full]
    names = [ax for ax, _ in full]
    dev_array = np.asarray(devices[:n]).reshape(dims)
    return Mesh(dev_array, axis_names=names)


def to_varying(x, axis_name: str):
    """Mark a shard_map value as device-varying over `axis_name` (jax 0.9's
    vma type system needs loop carries pre-marked). pvary→pcast rename compat."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    return jax.lax.pvary(x, axis_name)


def use_mesh(mesh: Mesh):
    """Context manager making `mesh` the ambient mesh (jax>=0.9 renamed
    use_mesh → set_mesh; accept either)."""
    setter = getattr(jax.sharding, "set_mesh", None) or jax.sharding.use_mesh
    return setter(mesh)


def auto_mesh_shape(n_devices: int, tp: int | None = None) -> dict[str, int]:
    """Factor n_devices into {data, model}. If tp is not given, pick the
    largest power-of-two TP degree ≤ 8 that divides n_devices — TP wants to
    stay within one ICI domain; the rest goes to DP."""
    if tp is None:
        tp = 1
        while tp < 8 and (n_devices % (tp * 2) == 0):
            tp *= 2
    if n_devices % tp:
        raise ValueError(f"tp={tp} does not divide n_devices={n_devices}")
    return {AXIS_DATA: n_devices // tp, AXIS_MODEL: tp}
