"""Device meshes for the TPU build.

The reference's only distribution axes are HTTP-coordinated agent processes
and a Go worker pool (reference: internal/handlers/execute.go:1341-1386, SURVEY
§2.4) — tensor math happened in external providers. Here the compute scales
over a ``jax.sharding.Mesh``: XLA inserts ICI/DCN collectives from sharding
annotations; the control plane never touches tensor traffic.

Canonical axis names (used by every PartitionSpec in the repo):

- ``data``    — batch/data parallelism (DP)
- ``model``   — tensor parallelism over heads / ffn dims (TP, rides ICI)
- ``seq``     — sequence/context parallelism (SP/CP, ring attention)
- ``expert``  — expert parallelism for MoE layers (EP)
- ``stage``   — pipeline stages across slices (PP, rides DCN)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"

# Mesh axis order: slower-varying axes first so that `model` (the most
# bandwidth-hungry axis) maps to physically adjacent devices on the ICI torus.
CANONICAL_ORDER = (AXIS_STAGE, AXIS_DATA, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)


def make_mesh(shape: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh from an {axis: size} dict. Axes are laid out in
    CANONICAL_ORDER; missing axes get size 1 (so PartitionSpecs referring to
    any canonical axis always resolve)."""
    if devices is None:
        devices = jax.devices()
    shape = dict(shape or {})
    n = int(np.prod(list(shape.values()))) if shape else len(devices)
    if not shape:
        shape = {AXIS_DATA: n}
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    full = [(ax, shape.get(ax, 1)) for ax in CANONICAL_ORDER]
    dims = [s for _, s in full]
    names = [ax for ax, _ in full]
    dev_array = np.asarray(devices[:n]).reshape(dims)
    return Mesh(dev_array, axis_names=names)


def to_varying(x, axis_name: str):
    """Mark a shard_map value as device-varying over `axis_name` (jax 0.9's
    vma type system needs loop carries pre-marked). pvary→pcast rename
    compat; jax < 0.6 has neither and needs no marking — identity."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_name)
    return x


def shard_map(f, mesh, in_specs, out_specs, **kwargs):
    """shard_map across jax versions: top-level ``jax.shard_map`` (>= 0.6)
    or ``jax.experimental.shard_map.shard_map`` (older). The old
    replication checker predates the vma marking to_varying relies on and
    false-positives on lax.cond carries, so it defaults off there."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn

        kwargs.setdefault("check_rep", False)
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def use_mesh(mesh: Mesh):
    """Context manager making `mesh` the ambient mesh (jax>=0.9 renamed
    use_mesh → set_mesh; accept either; on jax 0.4/0.5 the Mesh object is
    itself the ambient-mesh context manager)."""
    setter = getattr(jax.sharding, "set_mesh", None) or getattr(
        jax.sharding, "use_mesh", None
    )
    if setter is not None:
        return setter(mesh)
    return mesh


def make_hybrid_mesh(
    ici_shape: dict[str, int],
    dcn_shape: dict[str, int],
    devices=None,
) -> Mesh:
    """Multi-slice mesh: `ici_shape` axes stay within one slice (TP/SP/EP —
    bandwidth-hungry collectives ride the ICI torus), `dcn_shape` axes span
    slices (DP/PP — pipeline ppermute and gradient psum tolerate DCN
    latency). SURVEY §7 step 8's "multi-slice DCN placement".

    On real multi-slice TPU hardware this delegates to
    ``mesh_utils.create_hybrid_device_mesh`` (device order chosen so
    same-slice devices are contiguous along ICI axes); on hosts whose
    devices carry no slice topology (CPU test meshes, single slice) it
    falls back to a canonical-order reshape with identical axis semantics,
    so sharded programs compile the same either way."""
    overlap = set(ici_shape) & set(dcn_shape)
    if overlap:
        raise ValueError(f"axes cannot be both ICI and DCN: {sorted(overlap)}")
    unknown = (set(ici_shape) | set(dcn_shape)) - set(CANONICAL_ORDER)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; canonical axes are "
            f"{CANONICAL_ORDER}"
        )
    if devices is None:
        devices = jax.devices()
    # CANONICAL_ORDER keeps `model` fastest-varying (physically adjacent);
    # DCN axes order ahead of ICI axes within each group.
    dcn_axes = [ax for ax in CANONICAL_ORDER if ax in dcn_shape]
    ici_axes = [ax for ax in CANONICAL_ORDER if ax in ici_shape]
    names = dcn_axes + ici_axes
    ici_dims = [ici_shape[ax] for ax in ici_axes]
    dcn_dims = [dcn_shape[ax] for ax in dcn_axes]
    n = int(np.prod(ici_dims + dcn_dims))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    slice_ids = {getattr(d, "slice_index", None) for d in devices[:n]}
    if None in slice_ids or len(slice_ids) < 2:
        # No slice topology metadata (CPU/virtual devices, single slice):
        # plain reshape preserves axis semantics for compile-level validation.
        dev_array = np.asarray(devices[:n]).reshape(dcn_dims + ici_dims)
        return Mesh(dev_array, axis_names=names)
    from jax.experimental import mesh_utils

    # Real multi-slice hardware: let create_hybrid_device_mesh place devices
    # (errors here are genuine misconfigurations — a wrong dcn shape must
    # NOT silently degrade to a reshape that routes `model` collectives over
    # DCN). It multiplies the two shapes elementwise over ONE axis list:
    # each axis is pure-DCN (ici part 1) or pure-ICI (dcn part 1) here, so
    # the product recovers our dims.
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=[1] * len(dcn_dims) + ici_dims,
        dcn_mesh_shape=dcn_dims + [1] * len(ici_dims),
        devices=devices[:n],
    )
    return Mesh(dev_array, axis_names=names)


def auto_mesh_shape(n_devices: int, tp: int | None = None) -> dict[str, int]:
    """Factor n_devices into {data, model}. If tp is not given, pick the
    largest power-of-two TP degree ≤ 8 that divides n_devices — TP wants to
    stay within one ICI domain; the rest goes to DP."""
    if tp is None:
        tp = 1
        while tp < 8 and (n_devices % (tp * 2) == 0):
            tp *= 2
    if n_devices % tp:
        raise ValueError(f"tp={tp} does not divide n_devices={n_devices}")
    return {AXIS_DATA: n_devices // tp, AXIS_MODEL: tp}
