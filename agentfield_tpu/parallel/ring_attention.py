"""Ring attention: sequence/context parallelism over an ICI ring.

The reference has no long-context story at all — it trims prompts to the
provider window (sdk/python/agentfield/agent_ai.py:262-325, SURVEY §5
long-context row). Here sequences shard over the mesh's ``seq`` axis: each
device holds a [B, S/n, H, hd] slice of Q/K/V, computes blockwise attention
against its resident K/V block, and rotates K/V around the ring with
``ppermute`` while folding results into online-softmax statistics — peak
memory O(S/n · S/n) per device, full-sequence attention without any device
ever materializing the whole context.

Causality uses the block structure: a Q block attends K blocks from earlier
ring positions fully, its own block causally, later blocks not at all —
whole-block skips drop the FLOPs entirely (lax.cond), while the ppermute
still runs every step so the ring stays in lockstep.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from agentfield_tpu.parallel.mesh import AXIS_SEQ, to_varying
from agentfield_tpu.parallel.mesh import shard_map as shard_map_compat

_NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, causal, window=None):
    """One Q-block × K-block partial attention. q: [B, Sq, H, hd];
    k/v: [B, Sk, Kh, hd]; positions: [B, Sq]/[B, Sk] global. Returns
    (scores_max [B,H,Sq,1], exp_sum [B,H,Sq,1], acc [B,Sq,H,hd])."""
    B, Sq, H, hd = q.shape
    Kh = k.shape[2]
    rep = H // Kh
    qg = q.reshape(B, Sq, Kh, rep, hd).astype(jnp.float32) * (hd**-0.5)
    s = jnp.einsum("bskrh,btkh->bkrst", qg, k.astype(jnp.float32))  # [B,Kh,rep,Sq,Sk]
    if causal:
        mask = k_pos[:, None, :] <= q_pos[:, :, None]  # [B, Sq, Sk]
        if window is not None:  # HF Mistral semantics (attention_ref)
            mask &= k_pos[:, None, :] > q_pos[:, :, None] - window
        s = jnp.where(mask[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,Kh,rep,Sq,1]
    # Guard fully-masked rows: exp(-inf - -inf) would be NaN.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bkrst,btkh->bskrh", p, v.astype(jnp.float32))  # [B,Sq,Kh,rep,hd]
    return m_safe, l, acc.reshape(B, Sq, H, hd)


def _ring_attention_local(
    q, k, v, positions, axis_name: str, causal: bool, window: int | None = None
):
    """Body run per-device under shard_map. All inputs are local shards
    [B, S_local, ...]; `positions` [B, S_local] are the GLOBAL positions of
    this shard's tokens — they travel the ring alongside K/V, so the causal
    mask is position-exact (identical semantics to attention_ref), including
    offset/continuation position layouts. The whole-block skip assumes
    positions are STRICTLY increasing along the global sequence (the
    sharding contract; see ring_attention's docstring)."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Sq, H, hd = q.shape

    def step(i, carry):
        m, l, acc, cur_k, cur_v, cur_pos = carry
        # K/V currently resident arrived from ring position (my_idx - i).
        src_idx = (my_idx - i) % n
        q_pos = positions
        k_pos = cur_pos

        def attend(args):
            m, l, acc = args
            bm, bl, bacc = _block_attend(
                q, cur_k, cur_v, q_pos, k_pos, causal, window=window
            )
            bm = bm.reshape(B, -1, Sq, 1)  # [B, H, Sq, 1] (Kh*rep == H)
            bl = bl.reshape(B, -1, Sq, 1)
            # Online-softmax merge with the running statistics.
            m_new = jnp.maximum(m, bm)
            alpha_old = jnp.exp(m - m_new)
            alpha_blk = jnp.exp(bm - m_new)
            l_new = l * alpha_old + bl * alpha_blk
            ao = alpha_old.transpose(0, 2, 1, 3)  # [B, Sq, H, 1]
            ab = alpha_blk.transpose(0, 2, 1, 3)
            return m_new, l_new, acc * ao + bacc * ab

        if causal:
            # Blocks wholly above the diagonal (src after me on the ring)
            # contribute nothing: skip their FLOPs, not just mask them. With
            # a sliding window, blocks wholly BEFORE every query's window
            # skip too (positions ride the ring, so the bound is exact). The
            # ppermute below stays unconditional — the ring must stay in
            # lockstep.
            run = src_idx <= my_idx
            if window is not None:
                run &= jnp.max(k_pos) > jnp.min(q_pos) - window
            m, l, acc = jax.lax.cond(run, attend, lambda a: a, (m, l, acc))
        else:
            m, l, acc = attend((m, l, acc))
        # Rotate K/V (and their positions) to the next ring neighbor.
        perm = [(j, (j + 1) % n) for j in range(n)]
        nxt_k = jax.lax.ppermute(cur_k, axis_name, perm)
        nxt_v = jax.lax.ppermute(cur_v, axis_name, perm)
        nxt_pos = jax.lax.ppermute(cur_pos, axis_name, perm)
        return m, l, acc, nxt_k, nxt_v, nxt_pos

    # The stats depend on axis_index, so the initial carry must already be
    # marked device-varying for shard_map's vma type system (jax >= 0.9).
    m0 = to_varying(jnp.full((B, H, Sq, 1), _NEG_INF, jnp.float32), axis_name)
    l0 = to_varying(jnp.zeros((B, H, Sq, 1), jnp.float32), axis_name)
    acc0 = to_varying(jnp.zeros((B, Sq, H, hd), jnp.float32), axis_name)
    m, l, acc, _, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v, positions))
    l = jnp.maximum(l, 1e-30).transpose(0, 2, 1, 3)  # [B, Sq, H, 1]
    return (acc / l).astype(q.dtype)


@functools.partial(
    jax.jit, static_argnames=("mesh", "causal", "axis_name", "window")
)
def ring_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, Kh, hd]
    v: jax.Array,  # [B, S, Kh, hd]
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = AXIS_SEQ,
    positions: jax.Array | None = None,  # [B, S] global positions; default
    # arange(S) — provide explicitly for offset/continuation layouts so the
    # causal mask stays position-exact (identical to attention_ref)
    window: int | None = None,  # sliding window (Mistral semantics): ring
    # blocks wholly before a shard's window skip their FLOPs entirely, so a
    # bound window visits O(window / shard_len) ring steps' worth of compute
) -> jax.Array:
    """Full-sequence attention with S sharded over `axis_name`. S must divide
    evenly by the axis size; positions must be STRICTLY increasing along the
    sequence (the causal whole-block skip is ring-index-based, so tied
    positions straddling a shard boundary would skip keys attention_ref
    attends). Heads
    stay replicated across the seq axis (they may simultaneously be sharded
    over `model` by the caller's outer pjit)."""
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(f"sequence {q.shape[1]} not divisible by {axis_name}={n}")
    if n == 1:
        import warnings

        warnings.warn(
            f"ring_attention with {axis_name} axis of size 1 is plain attention "
            "— size the axis to actually shard the sequence",
            stacklevel=2,
        )
    if positions is None:
        positions = jnp.arange(q.shape[1], dtype=jnp.int32)[None].repeat(q.shape[0], 0)
    spec = P(None, axis_name, None, None)
    pos_spec = P(None, axis_name)
    if window is not None and not causal:
        raise ValueError("window requires causal=True (HF Mistral semantics)")
    fn = shard_map_compat(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec),
        out_specs=spec,
    )
    return fn(q, k, v, positions)
