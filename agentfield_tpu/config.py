"""Configuration system: YAML file + environment overrides.

Mirrors the reference's config surface (internal/config/config.go:15-180 —
server port, execution queue tuning, cleanup, storage, CORS/data dirs, with
viper env overrides). Env vars use the AGENTFIELD_ prefix with __ as the
section separator, e.g. AGENTFIELD_SERVER__PORT=9000 overrides server.port.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any

import yaml

ENV_PREFIX = "AGENTFIELD_"


@dataclasses.dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 8800
    db_path: str = "~/.agentfield_tpu/control_plane.db"
    webhook_secret: str | None = None
    keystore_passphrase: str | None = None  # None → AGENTFIELD_KEYSTORE_PASSPHRASE env


@dataclasses.dataclass
class ExecutionConfig:
    agent_timeout: float = 90.0  # reference: execute.go:187
    sync_wait_timeout: float = 600.0
    async_workers: int = 8
    queue_capacity: int = 1024  # reference: execute.go:1373
    cleanup_interval: float = 60.0
    stale_after: float = 3600.0
    retention: float = 86400.0


@dataclasses.dataclass
class PresenceConfig:
    heartbeat_ttl: float = 300.0  # reference: server.go:131-137
    sweep_interval: float = 30.0
    evict_after: float = 1800.0


@dataclasses.dataclass
class ModelNodeConfig:
    model: str = "llama-3.2-1b"
    checkpoint: str | None = None  # HF checkpoint dir (safetensors)
    lora: str | None = None  # LoRA adapter dir (training.lora.save_adapter),
    # merged into the base weights at load
    tokenizer: str | None = None
    max_batch: int = 32
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 32
    attn_impl: str = "ref"
    prefill_impl: str = "ref"
    prefill_chunk: int | None = None  # chunked prefill (>= 16) or whole-prompt
    decode_span: int = 1  # decode steps per device dispatch (one token
    # readback per span — set 8-16 on high-latency device links)
    kv_quant_dtype: str = "none"  # quantized KV pages: "int8" | "fp8"
    # store K/V pages quantized with per-slot scales (~2x pages per HBM
    # byte; docs/KERNELS.md "Quantized pages"). (The old kv_write_impl
    # alias is removed — attn_impl="pallas" selects the fused kernel.)
    grammar_slots: int = 256  # constrained-decoding bank rows (0 disables)
    grammar_whitespace: bool = False  # accept bounded whitespace in
    # schema-constrained output (pretty-printed JSON) instead of canonical
    # compact form
    vision: str | None = None  # vision tower config name → serve image inputs
    audio: str | None = None  # audio tower config name → serve audio inputs
    tts: str | None = None  # TTS head config name → serve audio OUTPUT
    imagegen: str | None = None  # image-gen head config name → serve
    # output="image" rendering
    quant: str | None = None  # "int8" weight-only quantized serving
    spec_draft: str | None = None  # draft preset/checkpoint for speculative
    # decoding (with spec_k > 0)
    spec_k: int = 0  # speculative proposals per decode step (0 disables)
    tp: int = 1  # tensor-parallel degree over the `model` mesh axis


@dataclasses.dataclass
class Config:
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    execution: ExecutionConfig = dataclasses.field(default_factory=ExecutionConfig)
    presence: PresenceConfig = dataclasses.field(default_factory=PresenceConfig)
    model_node: ModelNodeConfig = dataclasses.field(default_factory=ModelNodeConfig)
    data_dir: str = "~/.agentfield_tpu"

    def expanded_data_dir(self) -> Path:
        return Path(os.path.expanduser(self.data_dir))


_SECTIONS = {
    "server": ServerConfig,
    "execution": ExecutionConfig,
    "presence": PresenceConfig,
    "model_node": ModelNodeConfig,
}


def _coerce(value: str, target_type: Any) -> Any:
    if target_type is bool or target_type == "bool":
        return value.lower() in ("1", "true", "yes")
    for t in (int, float):
        if target_type is t:
            return t(value)
    return value


def load_config(path: str | None = None, env: dict[str, str] | None = None) -> Config:
    """YAML (optional) then env overrides (AGENTFIELD_SECTION__FIELD)."""
    cfg = Config()
    if path:
        doc = yaml.safe_load(Path(path).read_text()) or {}
        if not isinstance(doc, dict):
            raise ValueError(f"config file {path} must contain a mapping")
        for section, cls in _SECTIONS.items():
            if section in doc and isinstance(doc[section], dict):
                known = {f.name for f in dataclasses.fields(cls)}
                unknown = set(doc[section]) - known
                if unknown:
                    raise ValueError(f"unknown keys in [{section}]: {sorted(unknown)}")
                setattr(cfg, section, cls(**doc[section]))
        if "data_dir" in doc:
            cfg.data_dir = doc["data_dir"]

    env = env if env is not None else dict(os.environ)
    for key, value in env.items():
        if not key.startswith(ENV_PREFIX) or "__" not in key:
            continue
        section_name, _, field_name = key[len(ENV_PREFIX) :].lower().partition("__")
        if section_name not in _SECTIONS:
            continue
        section = getattr(cfg, section_name)
        for f in dataclasses.fields(section):
            if f.name == field_name:
                setattr(section, f.name, _coerce(value, f.type if isinstance(f.type, type) else type(getattr(section, f.name))))
    return cfg
