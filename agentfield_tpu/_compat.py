"""Backend/environment compatibility helpers."""

from __future__ import annotations

import asyncio
import os


class _TimeoutCM:
    """Python 3.10 stand-in for asyncio.timeout(): cancel the enclosing task
    at the deadline and surface builtin TimeoutError at block exit (the 3.11
    semantics — TimeoutError and asyncio.TimeoutError are aliases there)."""

    def __init__(self, delay: float | None):
        self._delay = delay
        self._handle = None
        self._timed_out = False

    async def __aenter__(self):
        self._task = asyncio.current_task()
        if self._delay is not None:
            self._handle = asyncio.get_running_loop().call_later(
                self._delay, self._on_timeout
            )
        return self

    def _on_timeout(self):
        self._timed_out = True
        self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            self._handle.cancel()
        if self._timed_out and exc_type in (
            asyncio.CancelledError,
            asyncio.TimeoutError,
        ):
            raise TimeoutError from exc
        return False


def aio_timeout(delay: float | None):
    """``async with aio_timeout(t):`` — asyncio.timeout() on Python >= 3.11,
    a task-cancelling backport on 3.10. Always raises the BUILTIN
    TimeoutError on expiry, so ``except TimeoutError`` works on both."""
    native = getattr(asyncio, "timeout", None)
    if native is not None:
        return native(delay)
    return _TimeoutCM(delay)


def force_cpu_backend(virtual_devices: int | None = None) -> None:
    """Force the CPU backend even when the image preloads a TPU plugin.

    This image's sitecustomize imports jax at *interpreter start* (the axon
    TPU tunnel), so jax's config has already latched JAX_PLATFORMS from the
    environment and plain env assignment is too late. jax.config.update still
    works because *backends* initialize lazily, on first use — which is after
    any caller of this helper. XLA_FLAGS is read by the CPU client at
    backend-init time, so setting it here is also still effective.

    Must be called before the first jax computation / jax.devices() call.
    """
    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={virtual_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
