"""Backend/environment compatibility helpers."""

from __future__ import annotations

import os


def force_cpu_backend(virtual_devices: int | None = None) -> None:
    """Force the CPU backend even when the image preloads a TPU plugin.

    This image's sitecustomize imports jax at *interpreter start* (the axon
    TPU tunnel), so jax's config has already latched JAX_PLATFORMS from the
    environment and plain env assignment is too late. jax.config.update still
    works because *backends* initialize lazily, on first use — which is after
    any caller of this helper. XLA_FLAGS is read by the CPU client at
    backend-init time, so setting it here is also still effective.

    Must be called before the first jax computation / jax.devices() call.
    """
    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={virtual_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
