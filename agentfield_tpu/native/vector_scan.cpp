// Native vector-similarity scan for the control plane's vector memory.
//
// The reference computes cosine/dot/L2 over all rows in Go
// (internal/storage/vector_store_sqlite.go:79); here the scan is C++ built
// -O3 so the compiler vectorizes the inner loops, with a bounded top-k
// selection instead of a full sort. Exposed extern "C" for ctypes
// (pybind11 is not in this image).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Hit {
    float score;
    int32_t idx;
};

// Maintain the k best hits in a small array (k is tiny; linear insert beats
// heap bookkeeping at these sizes).
inline void push_topk(std::vector<Hit>& heap, int k, float score, int32_t idx) {
    if ((int)heap.size() < k) {
        heap.push_back({score, idx});
        for (size_t i = heap.size() - 1; i > 0 && heap[i].score > heap[i - 1].score; --i) {
            Hit t = heap[i];
            heap[i] = heap[i - 1];
            heap[i - 1] = t;
        }
        return;
    }
    if (score <= heap.back().score) return;
    heap.back() = {score, idx};
    for (size_t i = heap.size() - 1; i > 0 && heap[i].score > heap[i - 1].score; --i) {
        Hit t = heap[i];
        heap[i] = heap[i - 1];
        heap[i - 1] = t;
    }
}

}  // namespace

extern "C" {

// metric: 0 = cosine, 1 = dot, 2 = negative-L2
// mat: [n, d] row-major float32; q: [d]; out_idx/out_score: [k]
// returns the number of results written (min(n, k)), or -1 on bad args.
int32_t af_vector_scan_topk(const float* mat, int32_t n, int32_t d, const float* q,
                            int32_t metric, int32_t k, int32_t* out_idx,
                            float* out_score) {
    if (!mat || !q || !out_idx || !out_score || n < 0 || d <= 0 || k <= 0 || metric < 0 ||
        metric > 2)
        return -1;

    float qnorm = 0.f;
    if (metric == 0) {
        for (int32_t j = 0; j < d; ++j) qnorm += q[j] * q[j];
        qnorm = std::sqrt(qnorm) + 1e-12f;
    }

    std::vector<Hit> best;
    best.reserve(k);
    for (int32_t i = 0; i < n; ++i) {
        const float* row = mat + (size_t)i * d;
        float score;
        if (metric == 2) {
            float acc = 0.f;
            for (int32_t j = 0; j < d; ++j) {
                float diff = row[j] - q[j];
                acc += diff * diff;
            }
            score = -std::sqrt(acc);
        } else {
            float dot = 0.f, rnorm = 0.f;
            for (int32_t j = 0; j < d; ++j) {
                dot += row[j] * q[j];
                rnorm += row[j] * row[j];
            }
            score = (metric == 0) ? dot / (std::sqrt(rnorm) * qnorm + 1e-12f) : dot;
        }
        push_topk(best, k, score, i);
    }
    int32_t m = (int32_t)best.size();
    for (int32_t i = 0; i < m; ++i) {
        out_idx[i] = best[i].idx;
        out_score[i] = best[i].score;
    }
    return m;
}
}
