"""ctypes bindings for the native (C++) runtime components.

First use triggers an in-tree `make` (g++ -O3, no external deps); failures
fall back to the numpy implementations so a missing toolchain never breaks
the control plane — the native path is a perf optimization, mirroring how
the reference keeps its Go scan simple (vector_store_sqlite.go:79).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "libafnative.so"
_METRICS = {"cosine": 0, "dot": 1, "l2": 2}

_lib: ctypes.CDLL | None = None
_tried = False


def build(timeout: float = 120) -> bool:
    """Compile the native library (blocking — call from a worker thread or at
    process start, never from an event loop). Returns availability."""
    global _tried
    try:
        if not _LIB_PATH.exists():
            subprocess.run(
                ["make", "-s"], cwd=_DIR, check=True, capture_output=True, timeout=timeout
            )
    except Exception:
        return False
    _tried = False  # allow _load to pick up the fresh artifact
    return _load() is not None


def _load() -> ctypes.CDLL | None:
    """Load the library if ALREADY BUILT — never compiles (request paths call
    this; a surprise 120s `make` inside the aiohttp event loop would stall
    heartbeats and evict live agents)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not _LIB_PATH.exists():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.af_vector_scan_topk.restype = ctypes.c_int32
        lib.af_vector_scan_topk.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None or build()


def vector_scan_topk(
    mat: np.ndarray, q: np.ndarray, metric: str = "cosine", k: int = 5
) -> tuple[np.ndarray, np.ndarray] | None:
    """Top-k (indices, scores) over rows of `mat` or None when the native
    library is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, np.float32)
    q = np.ascontiguousarray(q, np.float32)
    n, d = mat.shape
    k = min(k, n) if n else 0
    if k == 0:
        return np.empty((0,), np.int32), np.empty((0,), np.float32)
    out_idx = np.empty((k,), np.int32)
    out_score = np.empty((k,), np.float32)
    m = lib.af_vector_scan_topk(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        d,
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _METRICS[metric],
        k,
        out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_score.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if m < 0:
        return None
    return out_idx[:m], out_score[:m]
