"""Chained prefix block hashing — the ONE definition shared by the engine's
prefix page pool and the control plane's affinity router.

The serving side (``serving/kv_cache.PrefixPagePool``) content-addresses KV
pages by chained blake2b-128 block hashes; the gateway scores dispatch
candidates by how much of a request's leading hash chain a node's published
prefix sketch covers (docs/PREFIX_CACHING.md "Cluster tier"). Both sides must
chain the SAME bytes the SAME way or affinity scores silently read zero, so
the functions live here — a module with no jax/engine dependency the
control plane can import without dragging the serving stack onto the
gateway's event loop.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

# Bytes of blake2b digest kept per chain link (full collision margin for
# content addressing); the heartbeat sketch truncates further to
# SKETCH_DIGEST_BYTES — routing only, verified again at lookup.
DIGEST_BYTES = 16
SKETCH_DIGEST_BYTES = 8


def chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    """Chained block hash over one full page of token ids (vLLM/SGLang-style):
    a page's identity is (everything before it, its own tokens), so two
    requests share a page iff their prompts agree on the ENTIRE prefix
    through that page. blake2b-128 makes accidental collisions negligible;
    lookups still verify token content, so a collision degrades to a miss,
    never to wrong KV."""
    h = hashlib.blake2b(prev, digest_size=DIGEST_BYTES)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


def page_chain_hashes(tokens: Sequence[int], page_size: int) -> list[bytes]:
    """Chained hash per full page of `tokens`. Callers that probe the index
    repeatedly (the scheduler, every admission tick) compute this once per
    request and pass it to peek()/lookup() instead of re-hashing the prompt
    each tick."""
    out: list[bytes] = []
    h = b""
    for off in range(0, (len(tokens) // page_size) * page_size, page_size):
        h = chain_hash(h, tokens[off : off + page_size])
        out.append(h)
    return out


def sketch_digest(chain: bytes) -> str:
    """The truncated hex form of a chain hash as it appears in a node's
    heartbeat prefix sketch (docs/PREFIX_CACHING.md "Cluster tier"). 8 bytes
    is plenty for a routing signal: a cross-node false positive only costs a
    mis-routed request one ordinary prefill."""
    return chain[:SKETCH_DIGEST_BYTES].hex()
