from agentfield_tpu.training.trainer import (  # noqa: F401
    TrainState,
    causal_lm_loss,
    make_train_step,
    init_train_state,
)
