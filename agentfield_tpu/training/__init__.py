from agentfield_tpu.training.trainer import (  # noqa: F401
    TrainState,
    causal_lm_loss,
    make_train_step,
    init_train_state,
)
from agentfield_tpu.training.lora import (  # noqa: F401
    LoRAConfig,
    init_lora_params,
    init_lora_state,
    lora_pspecs,
    make_lora_train_step,
    merge_lora,
    load_adapter,
    save_adapter,
)
