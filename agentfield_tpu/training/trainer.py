"""Sharded training step (fine-tuning path).

The reference has no training at all (models live behind external APIs); this
is a new first-class component per SURVEY §2.4. Design: pure-functional optax
step under one ``jax.jit`` — params/opt-state carry NamedShardings (TP over
``model``, batch over ``data``), so XLA emits the reduce-scatter/all-reduce
pattern over ICI with no hand-written collectives.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models.llama import forward, init_params
from agentfield_tpu.parallel.mesh import AXIS_DATA
from agentfield_tpu.parallel.sharding import named_sharding, param_pspecs


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def causal_lm_loss(
    params,
    cfg: LlamaConfig,
    batch: dict[str, jax.Array],
    attn_impl: str = "ref",
    mesh=None,
):
    """Masked next-token cross-entropy. batch: tokens/positions/targets [B,S];
    targets < 0 are ignored (padding). attn_impl="ring" (+mesh) trains with
    the sequence sharded over the `seq` axis — long-context fine-tuning."""
    logits, _ = forward(
        params,
        cfg,
        batch["tokens"],
        batch["positions"],
        collect_kv=False,
        remat=True,
        attn_impl=attn_impl,
        mesh=mesh,
    )
    targets = batch["targets"]
    mask = (targets >= 0).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, jnp.maximum(targets, 0)[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = -(ll * mask).sum() / denom
    return loss, {"loss": loss, "tokens": mask.sum()}


def init_state_sharded(
    init_fn,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    pspecs=None,
) -> TrainState:
    """Shared init idiom: initialize a param tree directly sharded on the
    mesh (jit with out_shardings, so a 70B init never materializes
    unsharded) and derive opt-state with matching placement. Used by the
    full-params trainer AND the LoRA adapter state."""
    if mesh is None:
        params = init_fn(key)
    else:
        shardings = named_sharding(mesh, pspecs)
        params = jax.jit(init_fn, out_shardings=shardings)(key)
    opt_state = optimizer.init(params)  # moments inherit param shardings
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32))


def init_train_state(
    cfg: LlamaConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    dtype: str | None = None,
) -> TrainState:
    return init_state_sharded(
        lambda k: init_params(cfg, k, dtype), key, optimizer, mesh,
        param_pspecs(cfg) if mesh is not None else None,
    )


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    attn_impl: str = "ref",
    mesh=None,
):
    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, batch: dict[str, jax.Array]):
        grad_fn = jax.value_and_grad(causal_lm_loss, has_aux=True)
        (loss, metrics), grads = grad_fn(state.params, cfg, batch, attn_impl, mesh)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), metrics

    return train_step


def make_lm_batch(tokens: jax.Array) -> dict[str, jax.Array]:
    """Standard next-token LM batch from [B, S] tokens: arange positions,
    roll(-1) targets with the final column masked (-1 sentinel)."""
    B, S = tokens.shape
    return {
        "tokens": tokens,
        "positions": jnp.arange(S, dtype=jnp.int32)[None].repeat(B, 0),
        "targets": jnp.roll(tokens, -1, axis=1).at[:, -1].set(-1),
    }


def shard_batch(batch: dict[str, jax.Array], mesh: Mesh) -> dict[str, jax.Array]:
    """Place a host batch with the batch dim split over the ``data`` axis."""
    sharding = jax.sharding.NamedSharding(mesh, P(AXIS_DATA, None))
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}
