"""LoRA fine-tuning: low-rank adapters over the stacked-layer param tree.

The reference cannot fine-tune at all (its models live behind provider
APIs); here adaptation is a first-class loop: train adapters on the TPU
mesh, merge them into the base weights, and serve the merged model through
the same engine — `fine-tune → merge → serve` with no external tooling.

TPU-first design notes:

- Adapters attach to the stacked layer weights ([L, in, out] → a: [L, in, r],
  b: [L, r, out] with b zero-init, so step 0 is exactly the base model).
  The contribution is ``(x @ a) @ b * alpha/rank`` — but rather than
  rewriting the forward, the loss merges ``w + a @ b * scale`` per step:
  one [L, in, out] einsum per target that XLA fuses into the existing
  scan, keeping ONE forward implementation for base/LoRA/serving.
- What LoRA buys here is the OPTIMIZER memory: adam moments exist only for
  the adapter tree (rank·(in+out) per target instead of in·out — ~0.5% of
  an 8B model at r=16), plus tiny checkpoints and instant adapter swaps.
  The per-step merged copy of targeted weights is transient activation
  memory under remat, not a second resident set of moments.
- Sharding composes with TP: ``a`` replicates (rank ≪ in), ``b`` shards its
  out-dim exactly like the base weight, so the merge einsum needs no
  resharding and grads ride the same collectives as the base step.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.parallel.mesh import AXIS_MODEL
from agentfield_tpu.training.trainer import TrainState, causal_lm_loss

# target name → (in_dim, out_dim) resolver over the config
_TARGET_DIMS = {
    "wq": lambda c: (c.hidden_size, c.q_dim),
    "wk": lambda c: (c.hidden_size, c.kv_dim),
    "wv": lambda c: (c.hidden_size, c.kv_dim),
    "wo": lambda c: (c.q_dim, c.hidden_size),
    "w_gate": lambda c: (c.hidden_size, c.intermediate_size),
    "w_up": lambda c: (c.hidden_size, c.intermediate_size),
    "w_down": lambda c: (c.intermediate_size, c.hidden_size),
}

# base-weight out-dim sharding (mirror of parallel/sharding.py param_pspecs):
# b's out axis shards where the base weight's out axis shards
_OUT_SHARDED = {"wq", "wk", "wv", "w_gate", "w_up"}  # wo/w_down shard IN


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo")
    dtype: str = "float32"  # adapters train in f32 regardless of base dtype

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _check_targets(cfg: LlamaConfig, lcfg: LoRAConfig) -> None:
    unknown = set(lcfg.targets) - set(_TARGET_DIMS)
    if unknown:
        raise ValueError(f"unknown LoRA targets {sorted(unknown)}; have {sorted(_TARGET_DIMS)}")
    if cfg.num_experts > 0 and set(lcfg.targets) & {"w_gate", "w_up", "w_down"}:
        raise ValueError(
            "MoE expert stacks are not LoRA targets (per-expert adapters are "
            "not implemented) — target the attention projections instead"
        )
    if lcfg.rank < 1:
        raise ValueError(f"rank={lcfg.rank} must be >= 1")


def init_lora_params(cfg: LlamaConfig, lcfg: LoRAConfig, key: jax.Array) -> Any:
    """Adapter tree: {"layers": {"<t>_a": [L, in, r], "<t>_b": [L, r, out]}}.
    ``b`` is zero-init (merged model == base model at step 0 — the standard
    LoRA identity-start)."""
    _check_targets(cfg, lcfg)
    dt = jnp.dtype(lcfg.dtype)
    L, r = cfg.num_layers, lcfg.rank
    keys = jax.random.split(key, len(lcfg.targets))
    layers: dict[str, jax.Array] = {}
    for k, t in zip(keys, lcfg.targets):
        d_in, d_out = _TARGET_DIMS[t](cfg)
        layers[f"{t}_a"] = (
            jax.random.normal(k, (L, d_in, r), jnp.float32) * (1.0 / r)
        ).astype(dt)
        layers[f"{t}_b"] = jnp.zeros((L, r, d_out), dt)
    return {"layers": layers}


def lora_pspecs(cfg: LlamaConfig, lcfg: LoRAConfig) -> Any:
    """PartitionSpecs matching init_lora_params: ``a`` replicated (rank is
    tiny), ``b``'s out axis sharded exactly like the base weight's sharded
    axis — the merge einsum then composes with TP without resharding."""
    _check_targets(cfg, lcfg)
    layers: dict[str, P] = {}
    for t in lcfg.targets:
        layers[f"{t}_a"] = P(None, None, None)
        layers[f"{t}_b"] = (
            P(None, None, AXIS_MODEL) if t in _OUT_SHARDED else P(None, None, None)
        )
    return {"layers": layers}


def merge_lora(params: Any, lora: Any, lcfg: LoRAConfig) -> Any:
    """base + adapters → merged params (same tree shape as the base).
    Used per-step inside the LoRA loss AND once at serve time — one merge
    definition, so training and serving cannot drift."""
    merged_layers = dict(params["layers"])
    for name, a in lora["layers"].items():
        if not name.endswith("_a"):
            continue
        t = name[:-2]
        b = lora["layers"][t + "_b"]
        base = merged_layers[t]
        delta = jnp.einsum("lir,lro->lio", a.astype(jnp.float32), b.astype(jnp.float32))
        merged_layers[t] = (base.astype(jnp.float32) + delta * lcfg.scale).astype(base.dtype)
    return {**params, "layers": merged_layers}


def make_lora_train_step(
    cfg: LlamaConfig,
    lcfg: LoRAConfig,
    optimizer: optax.GradientTransformation,
    attn_impl: str = "ref",
    mesh=None,
):
    """LoRA step: gradients (and optimizer moments) exist ONLY for the
    adapter tree; the base params are a frozen input. State is a TrainState
    over the ADAPTERS."""
    _check_targets(cfg, lcfg)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def lora_step(state: TrainState, base_params: Any, batch: dict[str, jax.Array]):
        def loss_fn(lora):
            merged = merge_lora(base_params, lora, lcfg)
            return causal_lm_loss(merged, cfg, batch, attn_impl, mesh)

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        lora = optax.apply_updates(state.params, updates)
        return TrainState(lora, opt_state, state.step + 1), metrics

    return lora_step


def init_lora_state(
    cfg: LlamaConfig,
    lcfg: LoRAConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh=None,
) -> TrainState:
    from agentfield_tpu.training.trainer import init_state_sharded

    return init_state_sharded(
        lambda k: init_lora_params(cfg, lcfg, k), key, optimizer, mesh,
        lora_pspecs(cfg, lcfg) if mesh is not None else None,
    )


def save_adapter(path, lora: Any, lcfg: LoRAConfig) -> None:
    """Persist an adapter as a standalone artifact: the orbax tree plus a
    lora_config.json carrying the LoRAConfig AND every leaf's shape/dtype,
    so load_adapter needs no model config to rebuild the abstract tree.
    Adapter artifacts are tiny (rank·(in+out) per target) — cheap to ship
    and instant to swap."""
    import json
    from pathlib import Path as _Path

    import orbax.checkpoint as ocp

    path = _Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        # force: re-saving to one adapter dir is the natural periodic-persist
        # flow; orbax otherwise refuses to overwrite the fixed subpath
        ckptr.save(path / "adapter", lora, force=True)
    meta = {
        "rank": lcfg.rank,
        "alpha": lcfg.alpha,
        "targets": list(lcfg.targets),
        "dtype": lcfg.dtype,
        "shapes": {
            k: list(v.shape) for k, v in lora["layers"].items()
        },
        "dtypes": {k: str(v.dtype) for k, v in lora["layers"].items()},
    }
    (path / "lora_config.json").write_text(json.dumps(meta, indent=1))


def load_adapter(path) -> tuple[LoRAConfig, Any]:
    """Inverse of save_adapter: (LoRAConfig, adapter tree)."""
    import json
    from pathlib import Path as _Path

    import orbax.checkpoint as ocp

    path = _Path(path).absolute()
    meta = json.loads((path / "lora_config.json").read_text())
    lcfg = LoRAConfig(
        rank=int(meta["rank"]),
        alpha=float(meta["alpha"]),
        targets=tuple(meta["targets"]),
        dtype=meta["dtype"],
    )
    abstract = {
        "layers": {
            k: jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(meta["dtypes"][k]))
            for k, shape in meta["shapes"].items()
        }
    }
    with ocp.StandardCheckpointer() as ckptr:
        lora = ckptr.restore(path / "adapter", abstract)
    return lcfg, lora
