"""Training/serving checkpoints via orbax.

The reference's durability is SQL rows + payload files (SURVEY §5
checkpoint/resume: "no model checkpoints (no models)"); the TPU build adds
real model checkpointing: orbax handles sharded pytrees natively, so a 70B
TrainState saves/restores directly to/from its mesh placement.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from agentfield_tpu.training.trainer import TrainState


def save_checkpoint(path: str | Path, state: TrainState) -> None:
    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path / f"step_{int(state.step)}", state)


def latest_step(path: str | Path) -> int | None:
    path = Path(path)
    steps = [int(p.name.split("_", 1)[1]) for p in path.glob("step_*") if p.is_dir()]
    return max(steps) if steps else None


def restore_checkpoint(path: str | Path, abstract_state: Any, step: int | None = None) -> TrainState:
    """`abstract_state` carries shapes/dtypes/shardings (e.g. from
    jax.eval_shape over init, with NamedShardings attached) so restore places
    shards directly on the mesh without a host round-trip."""
    path = Path(path).absolute()
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path / f"step_{step}", abstract_state)
    return TrainState(*restored) if not isinstance(restored, TrainState) else restored
