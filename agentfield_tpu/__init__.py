"""agentfield_tpu — a TPU-native agent orchestration framework.

Capabilities mirror the reference AgentField platform ("Kubernetes for AI
agents": control plane + polyglot agent nodes + async execution + workflow
DAG + shared memory + DID/VC audit), with the external-LLM execution path
replaced by an in-tree TPU serving backend (JAX/XLA/Pallas/pjit).

Subpackages
-----------
- ``models``        functional JAX model implementations (Llama family)
- ``ops``           Pallas TPU kernels (flash attention, paged attention)
- ``parallel``      device meshes, GSPMD sharding rules, ring attention
- ``serving``       paged KV cache + continuous-batching inference engine
- ``training``      sharded train step (fine-tuning path)
- ``control_plane`` the orchestration server (nodes, executions, memory, ...)
- ``sdk``           the agent-developer SDK (Agent, @reasoner, ai(), call())
"""

__version__ = "0.1.0"
