"""Vision tower: ViT patch encoder + projector for multimodal prompts.

The reference's multimodal path hands images to external providers
(sdk/python/agentfield/agent_ai.py:449-520 classifies args and forwards
base64 parts via litellm). Here the modality is SERVED in-tree: a compact
ViT encodes image patches into LLM-space embeddings that the serving engine
injects at placeholder positions of the prompt (LLaVA-style early fusion).

TPU-first: patchify is a reshape (no conv unrolling), the encoder is one
``lax.scan`` over stacked layer weights like the LM (models/llama.py), all
matmuls land on the MXU in bf16, and the patch count is static per config so
serving buckets stay compile-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 12
    num_heads: int = 16
    mlp_ratio: int = 4
    out_dim: int = 2048  # LLM hidden size the projector maps into
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


CONFIGS = {
    # capacity-parity tower for the flagship 1B preset
    "vit-base-224": VisionConfig(),
    # hermetic test tower: compiles in seconds on CPU; out_dim matches
    # llama-tiny's hidden_size so engine tests fuse without adapters
    "vit-tiny": VisionConfig(
        image_size=32, patch_size=8, hidden_size=64, num_layers=2,
        num_heads=4, out_dim=128,
    ),
}


def get_vision_config(name: str) -> VisionConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown vision config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]


def init_vision_params(cfg: VisionConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d, L = cfg.hidden_size, cfg.num_layers
    f = d * cfg.mlp_ratio
    keys = jax.random.split(key, 8)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "patch_embed": norm(keys[0], (cfg.patch_dim, d)),
        "pos_embed": norm(keys[1], (cfg.num_patches, d)),
        "layers": {
            "ln1_w": jnp.ones((L, d), dt),
            "ln1_b": jnp.zeros((L, d), dt),
            "ln2_w": jnp.ones((L, d), dt),
            "ln2_b": jnp.zeros((L, d), dt),
            "wqkv": norm(keys[2], (L, d, 3 * d)),
            "wo": norm(keys[3], (L, d, d)),
            "w1": norm(keys[4], (L, d, f)),
            "w2": norm(keys[5], (L, f, d)),
        },
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # two-layer GELU projector into LLM space (LLaVA-1.5-style mlp2x)
        "proj_w1": norm(keys[6], (d, cfg.out_dim)),
        "proj_w2": norm(keys[7], (cfg.out_dim, cfg.out_dim)),
    }


def _layer_norm(x: jax.Array, w: jax.Array, b: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def patchify(images: jax.Array, cfg: VisionConfig) -> jax.Array:
    """[B, H, W, 3] float in [0, 1] → [B, num_patches, patch_dim].
    Pure reshape/transpose — no gather, no conv."""
    B = images.shape[0]
    g, p = cfg.image_size // cfg.patch_size, cfg.patch_size
    x = images.reshape(B, g, p, g, p, 3)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, g * g, cfg.patch_dim)


def vision_encode(params: Params, cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """Encode images into LLM-space patch embeddings.

    images: [B, image_size, image_size, 3] float32 in [0, 1]
    returns: [B, num_patches, out_dim] in the tower dtype
    """
    dt = jnp.dtype(cfg.dtype)
    x = patchify(images.astype(dt), cfg) @ params["patch_embed"]
    x = x + params["pos_embed"]
    B, N, d = x.shape
    H = cfg.num_heads
    hd = d // H

    def body(x, lp):
        h = _layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
        qkv = (h @ lp["wqkv"]).reshape(B, N, 3, H, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum(
            "bnhd,bmhd->bhnm", q, k, preferred_element_type=jnp.float32
        ) * (hd**-0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        x = x + attn.reshape(B, N, d) @ lp["wo"]
        h = _layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
        x = x + jax.nn.gelu((h @ lp["w1"]).astype(jnp.float32)).astype(x.dtype) @ lp["w2"]
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    h = jax.nn.gelu((x @ params["proj_w1"]).astype(jnp.float32)).astype(x.dtype)
    return h @ params["proj_w2"]


vision_encode_jit = jax.jit(vision_encode, static_argnames=("cfg",))
