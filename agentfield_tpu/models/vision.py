"""Vision tower: ViT patch encoder + projector for multimodal prompts.

The reference's multimodal path hands images to external providers
(sdk/python/agentfield/agent_ai.py:449-520 classifies args and forwards
base64 parts via litellm). Here the modality is SERVED in-tree: a compact
ViT encodes image patches into LLM-space embeddings that the serving engine
injects at placeholder positions of the prompt (LLaVA-style early fusion).

TPU-first: patchify is a reshape (no conv unrolling), the encoder is one
``lax.scan`` over stacked layer weights like the LM (models/llama.py), all
matmuls land on the MXU in bf16, and the patch count is static per config so
serving buckets stay compile-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 12
    num_heads: int = 16
    mlp_ratio: int = 4
    out_dim: int = 2048  # LLM hidden size the projector maps into
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    class_token: bool = False  # CLIP prepends a learned CLS token (it
    # participates in attention, so patch outputs depend on it); the
    # returned features are the PATCH positions either way
    pre_ln: bool = False  # CLIP applies a layernorm to the embeddings
    # before the encoder (pre_layrnorm)
    final_ln: bool = True  # CLIP's last_hidden_state has NO final LN (its
    # post_layernorm only feeds the pooled CLS → loader sets False); SigLIP
    # applies post_layernorm to the whole last_hidden_state (loader sets True)
    act: str = "gelu_tanh"  # encoder MLP activation: "gelu_tanh" (HF
    # gelu_pytorch_tanh — SigLIP), "quick_gelu" (x·σ(1.702x) — OpenAI CLIP),
    # "gelu_exact" (erf)
    pixel_mean: tuple[float, float, float] | None = None  # CLIPImageProcessor
    # normalization, applied INSIDE encode so the wire contract stays
    # "[0, 1] floats in" for callers
    pixel_std: tuple[float, float, float] | None = None

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.class_token else 0)

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


CONFIGS = {
    # capacity-parity tower for the flagship 1B preset
    "vit-base-224": VisionConfig(),
    # hermetic test tower: compiles in seconds on CPU; out_dim matches
    # llama-tiny's hidden_size so engine tests fuse without adapters
    "vit-tiny": VisionConfig(
        image_size=32, patch_size=8, hidden_size=64, num_layers=2,
        num_heads=4, out_dim=128,
    ),
}


def get_vision_config(name: str) -> VisionConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown vision config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]


def init_vision_params(cfg: VisionConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d, L = cfg.hidden_size, cfg.num_layers
    f = d * cfg.mlp_ratio
    keys = jax.random.split(key, 8)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    out: Params = {
        "patch_embed": norm(keys[0], (cfg.patch_dim, d)),
        "pos_embed": norm(keys[1], (cfg.seq_len, d)),
        "layers": {
            "ln1_w": jnp.ones((L, d), dt),
            "ln1_b": jnp.zeros((L, d), dt),
            "ln2_w": jnp.ones((L, d), dt),
            "ln2_b": jnp.zeros((L, d), dt),
            "wqkv": norm(keys[2], (L, d, 3 * d)),
            "bqkv": jnp.zeros((L, 3 * d), dt),
            "wo": norm(keys[3], (L, d, d)),
            "bo": jnp.zeros((L, d), dt),
            "w1": norm(keys[4], (L, d, f)),
            "b1": jnp.zeros((L, f), dt),
            "w2": norm(keys[5], (L, f, d)),
            "b2": jnp.zeros((L, d), dt),
        },
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # two-layer GELU projector into LLM space (LLaVA-1.5-style mlp2x)
        "proj_w1": norm(keys[6], (d, cfg.out_dim)),
        "proj_w2": norm(keys[7], (cfg.out_dim, cfg.out_dim)),
    }
    if cfg.class_token:
        out["class_embed"] = norm(jax.random.split(keys[0])[1], (d,))
    if cfg.pre_ln:
        out["pre_ln_w"] = jnp.ones((d,), dt)
        out["pre_ln_b"] = jnp.zeros((d,), dt)
    return out


def _layer_norm(x: jax.Array, w: jax.Array, b: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def patchify(images: jax.Array, cfg: VisionConfig) -> jax.Array:
    """[B, H, W, 3] float in [0, 1] → [B, num_patches, patch_dim].
    Pure reshape/transpose — no gather, no conv."""
    B = images.shape[0]
    g, p = cfg.image_size // cfg.patch_size, cfg.patch_size
    x = images.reshape(B, g, p, g, p, 3)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, g * g, cfg.patch_dim)


def _act_fn(name: str):
    if name == "quick_gelu":  # OpenAI CLIP: x * sigmoid(1.702 x)
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    if name == "gelu_exact":
        return lambda x: jax.nn.gelu(x, approximate=False)
    if name == "gelu_tanh":
        return jax.nn.gelu
    raise ValueError(f"unknown act {name!r} (gelu_tanh | quick_gelu | gelu_exact)")


def vision_hidden(params: Params, cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """[B, H, W, 3] float in [0, 1] → [B, num_patches, hidden] encoder
    states at the PATCH positions (pre-projector). For a CLIP checkpoint
    these match HF's last_hidden_state[:, 1:] (CLS dropped); for SigLIP —
    which has no CLS — they match the full last_hidden_state."""
    dt = jnp.dtype(cfg.dtype)
    act = _act_fn(cfg.act)
    if cfg.pixel_mean is not None:
        mean = jnp.asarray(cfg.pixel_mean, jnp.float32)
        std = jnp.asarray(cfg.pixel_std or (1.0, 1.0, 1.0), jnp.float32)
        images = (images.astype(jnp.float32) - mean) / std
    x = patchify(images.astype(dt), cfg) @ params["patch_embed"]
    if "patch_bias" in params:  # SigLIP's conv stem carries a bias
        x = x + params["patch_bias"]
    B = x.shape[0]
    if cfg.class_token:
        cls = jnp.broadcast_to(params["class_embed"], (B, 1, x.shape[-1])).astype(x.dtype)
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"]
    if cfg.pre_ln:
        x = _layer_norm(x, params["pre_ln_w"], params["pre_ln_b"], cfg.layer_norm_eps)
    B, N, d = x.shape
    H = cfg.num_heads
    hd = d // H
    layers = params["layers"]
    if "bqkv" not in layers:  # pre-bias checkpoints upgrade to zero biases
        f = layers["w1"].shape[-1]
        L = layers["wqkv"].shape[0]
        zdt = layers["wqkv"].dtype
        layers = {
            **layers,
            "bqkv": jnp.zeros((L, 3 * d), zdt), "bo": jnp.zeros((L, d), zdt),
            "b1": jnp.zeros((L, f), zdt), "b2": jnp.zeros((L, d), zdt),
        }

    def body(x, lp):
        h = _layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
        qkv = (h @ lp["wqkv"] + lp["bqkv"]).reshape(B, N, 3, H, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum(
            "bnhd,bmhd->bhnm", q, k, preferred_element_type=jnp.float32
        ) * (hd**-0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        x = x + (attn.reshape(B, N, d) @ lp["wo"] + lp["bo"])
        h = _layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
        up = act((h @ lp["w1"] + lp["b1"]).astype(jnp.float32)).astype(x.dtype)
        x = x + (up @ lp["w2"] + lp["b2"])
        return x, None

    x, _ = jax.lax.scan(body, x, layers)
    if cfg.final_ln:
        x = _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    if cfg.class_token:
        x = x[:, 1:]  # features are the patch positions
    return x


def vision_encode(params: Params, cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """Encode images into LLM-space patch embeddings.

    images: [B, image_size, image_size, 3] float32 in [0, 1]
    returns: [B, num_patches, out_dim] in the tower dtype
    """
    x = vision_hidden(params, cfg, images)
    h = jax.nn.gelu((x @ params["proj_w1"]).astype(jnp.float32)).astype(x.dtype)
    return h @ params["proj_w2"]


vision_encode_jit = jax.jit(vision_encode, static_argnames=("cfg",))


def load_clip_vision(
    path: str, out_dim: int = 2048, dtype: str = "float32", key=None
) -> tuple[VisionConfig, Params]:
    """HF CLIP or SigLIP vision checkpoint directory → (VisionConfig,
    params) for this tower. The two flavors are auto-detected from the
    tensors: CLIP carries a CLS token + pre-LN + quick_gelu and its
    last_hidden_state has NO final LN; SigLIP has a biased conv stem, no
    CLS, tanh-gelu, and post_layernorm ON last_hidden_state. Either way the
    conv patch embedding refolds into the patchify matmul and the encoder
    loads exactly (verified against transformers by tests); the LLM-space
    projector stays random-init (the fusion adapter is what a LLaVA-style
    finetune trains).

    Reference capability: image parts ride external providers
    (sdk/python/agentfield/agent_ai.py:449-520); here the encoder runs
    in-tree with real pretrained weights.
    """
    import json
    from pathlib import Path as _Path

    from safetensors import safe_open

    p = _Path(path)
    doc = json.loads((p / "config.json").read_text())
    vc = doc.get("vision_config", doc)  # CLIPConfig nests; CLIPVisionConfig flat
    d = int(vc["hidden_size"])
    tensors: dict[str, "np.ndarray"] = {}
    found_any = False
    for f in sorted(p.glob("*.safetensors")):
        found_any = True
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                if "vision_model." in name:
                    tensors[name.split("vision_model.", 1)[1]] = sf.get_tensor(name)
    if not found_any:
        raise FileNotFoundError(f"no *.safetensors under {p}")
    if not tensors:
        raise KeyError(f"no vision_model tensors in {p} (not a CLIP/SigLIP checkpoint?)")
    # flavor detection: positive model_type signal first, tensor-shape
    # fallback for configs that omit it — anything else fails loudly
    mt = vc.get("model_type") or doc.get("model_type") or ""
    if "siglip" in mt:
        siglip = True
    elif "clip" in mt:
        siglip = False
    elif "pre_layrnorm.weight" in tensors:
        siglip = False
    elif "embeddings.patch_embedding.bias" in tensors:
        siglip = True
    else:
        raise ValueError(
            f"unrecognized vision checkpoint flavor (model_type={mt!r}; "
            "expected CLIP or SigLIP)"
        )
    act_name = vc.get("hidden_act", "gelu_pytorch_tanh" if siglip else "quick_gelu")
    act = {
        "quick_gelu": "quick_gelu",
        "gelu": "gelu_exact",
        "gelu_pytorch_tanh": "gelu_tanh",
    }.get(act_name)
    if act is None:
        raise ValueError(f"unsupported vision hidden_act={act_name!r}")
    # processor defaults (preprocessor_config.json when present)
    mean = (0.5, 0.5, 0.5) if siglip else (0.48145466, 0.4578275, 0.40821073)
    std = (0.5, 0.5, 0.5) if siglip else (0.26862954, 0.26130258, 0.27577711)
    prep = p / "preprocessor_config.json"
    if prep.exists():
        pdoc = json.loads(prep.read_text())
        mean = tuple(pdoc.get("image_mean", mean))
        std = tuple(pdoc.get("image_std", std))
    cfg = VisionConfig(
        image_size=int(vc["image_size"]),
        patch_size=int(vc["patch_size"]),
        hidden_size=d,
        num_layers=int(vc["num_hidden_layers"]),
        num_heads=int(vc["num_attention_heads"]),
        mlp_ratio=int(vc["intermediate_size"]) // d,
        out_dim=out_dim,
        layer_norm_eps=float(vc.get("layer_norm_eps", 1e-6 if siglip else 1e-5)),
        dtype=dtype,
        class_token=not siglip,
        pre_ln=not siglip,
        final_ln=siglip,  # SigLIP post_layernorm IS on last_hidden_state
        act=act,
        pixel_mean=mean,
        pixel_std=std,
    )

    def get(name: str):
        if name not in tensors:
            raise KeyError(f"missing vision tensor {name!r}")
        return tensors[name]

    dt = jnp.dtype(dtype)
    L = cfg.num_layers

    def stack(fmt: str, transpose: bool = True) -> jax.Array:
        mats = [get(fmt.format(i)) for i in range(L)]
        return jnp.asarray(np.stack([m.T if transpose else m for m in mats]), dt)

    wq = stack("encoder.layers.{}.self_attn.q_proj.weight")
    wk = stack("encoder.layers.{}.self_attn.k_proj.weight")
    wv = stack("encoder.layers.{}.self_attn.v_proj.weight")
    bq = stack("encoder.layers.{}.self_attn.q_proj.bias", transpose=False)
    bk = stack("encoder.layers.{}.self_attn.k_proj.bias", transpose=False)
    bv = stack("encoder.layers.{}.self_attn.v_proj.bias", transpose=False)
    layers = {
        "ln1_w": stack("encoder.layers.{}.layer_norm1.weight", transpose=False),
        "ln1_b": stack("encoder.layers.{}.layer_norm1.bias", transpose=False),
        "ln2_w": stack("encoder.layers.{}.layer_norm2.weight", transpose=False),
        "ln2_b": stack("encoder.layers.{}.layer_norm2.bias", transpose=False),
        "wqkv": jnp.concatenate([wq, wk, wv], axis=2),
        "bqkv": jnp.concatenate([bq, bk, bv], axis=1),
        "wo": stack("encoder.layers.{}.self_attn.out_proj.weight"),
        "bo": stack("encoder.layers.{}.self_attn.out_proj.bias", transpose=False),
        "w1": stack("encoder.layers.{}.mlp.fc1.weight"),
        "b1": stack("encoder.layers.{}.mlp.fc1.bias", transpose=False),
        "w2": stack("encoder.layers.{}.mlp.fc2.weight"),
        "b2": stack("encoder.layers.{}.mlp.fc2.bias", transpose=False),
    }
    # conv patch kernel [d, 3, p, p] → [p, p, 3, d] → the patchify matmul's
    # [patch_dim, d] (patchify flattens each patch as [p_row, p_col, chan])
    conv = get("embeddings.patch_embedding.weight")
    patch_w = jnp.asarray(
        np.transpose(conv, (2, 3, 1, 0)).reshape(cfg.patch_dim, d), dt
    )
    if key is None:
        key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)

    def rand(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    params: Params = {
        "patch_embed": patch_w,
        "pos_embed": jnp.asarray(get("embeddings.position_embedding.weight"), dt),
        "layers": layers,
        "proj_w1": rand(k1, (d, out_dim)),
        "proj_w2": rand(k2, (out_dim, out_dim)),
    }
    if siglip:
        params["patch_bias"] = jnp.asarray(get("embeddings.patch_embedding.bias"), dt)
        params["final_ln_w"] = jnp.asarray(get("post_layernorm.weight"), dt)
        params["final_ln_b"] = jnp.asarray(get("post_layernorm.bias"), dt)
    else:
        params["class_embed"] = jnp.asarray(get("embeddings.class_embedding"), dt)
        params["pre_ln_w"] = jnp.asarray(get("pre_layrnorm.weight"), dt)
        params["pre_ln_b"] = jnp.asarray(get("pre_layrnorm.bias"), dt)
        params["final_ln_w"] = jnp.ones((d,), dt)  # unused (final_ln=False)
        params["final_ln_b"] = jnp.zeros((d,), dt)
    return cfg, params
