"""Mixture-of-Experts FFN with expert parallelism (the `expert` mesh axis).

Completes the parallelism inventory (SURVEY §2.4 EP row: "only if MoE models
are added; GSPMD `expert` axis"). Expert weights carry a leading [E, ...]
axis sharded over ``expert``; each device computes its resident experts for
all tokens and a psum combines router-weighted outputs — a soft-routing
formulation (dense compute, exact) whose sharding layout is identical to
sparse-dispatch MoE; capacity-based top-k token dropping is the planned
optimization on the same layout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from agentfield_tpu.parallel.mesh import AXIS_EXPERT


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    expert_intermediate: int
    num_experts: int
    top_k: int = 2  # router mass concentrates on k experts (soft weights)


def init_moe_params(cfg: MoEConfig, key: jax.Array, dtype=jnp.float32) -> dict[str, Any]:
    ks = jax.random.split(key, 3)
    d, f, e = cfg.hidden_size, cfg.expert_intermediate, cfg.num_experts
    scale = 0.02
    return {
        "router": (jax.random.normal(ks[0], (d, e), jnp.float32) * scale).astype(dtype),
        "w_in": (jax.random.normal(ks[1], (e, d, f), jnp.float32) * scale).astype(dtype),
        "w_out": (jax.random.normal(ks[2], (e, f, d), jnp.float32) * scale).astype(dtype),
    }


def moe_pspecs() -> dict[str, P]:
    ex = AXIS_EXPERT
    return {"router": P(None, None), "w_in": P(ex, None, None), "w_out": P(ex, None, None)}


def moe_ffn(params: dict[str, Any], cfg: MoEConfig, x: jax.Array) -> jax.Array:
    """Reference (single-device) computation. x: [B, S, D] → [B, S, D]."""
    logits = (x @ params["router"]).astype(jnp.float32)  # [B, S, E]
    mask = topk_router_weights(logits, cfg.top_k)
    h = jnp.einsum("bsd,edf->besf", x, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("besf,efd->besd", h, params["w_out"])
    return jnp.einsum("besd,bse->bsd", y.astype(jnp.float32), mask).astype(x.dtype)


def topk_router_weights(logits: jax.Array, k: int) -> jax.Array:
    """[..., S, E] router logits → [..., S, E] routing weights: softmax over
    the top-k experts' logits, zero elsewhere (exactly HF Mixtral's
    softmax→top-k→renormalize). The ONE routing definition — serving
    (llama._moe_mlp), the dense reference (moe_ffn), and the EP shard body
    (_moe_local) all call it."""
    top, idx = jax.lax.top_k(logits, k)
    batch_idx = jnp.meshgrid(
        *[jnp.arange(n) for n in logits.shape[:-1]], indexing="ij"
    )
    return jnp.zeros_like(logits).at[
        tuple(b[..., None] for b in batch_idx) + (idx,)
    ].set(jax.nn.softmax(top, axis=-1))


def _moe_local(params, x, cfg: MoEConfig, axis: str):
    """Per-device body: my expert shard computes for ALL tokens; the router
    (replicated) masks non-resident experts' weights to zero and a psum
    combines across the expert axis."""
    e_local = params["w_in"].shape[0]
    my_idx = jax.lax.axis_index(axis)
    logits = (x @ params["router"]).astype(jnp.float32)  # [B, S, E_total]
    weights = topk_router_weights(logits, cfg.top_k)
    # Slice my experts' routing weights: experts [my_idx*e_local, ...).
    my_w = jax.lax.dynamic_slice_in_dim(weights, my_idx * e_local, e_local, axis=2)
    h = jnp.einsum("bsd,edf->besf", x, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("besf,efd->besd", h, params["w_out"])
    mine = jnp.einsum("besd,bse->bsd", y.astype(jnp.float32), my_w)
    return jax.lax.psum(mine, axis).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"))
def moe_ffn_sharded(params: dict[str, Any], cfg: MoEConfig, x: jax.Array, mesh: Mesh) -> jax.Array:
    """Expert-parallel MoE FFN over the `expert` mesh axis."""
    n = mesh.shape[AXIS_EXPERT]
    if cfg.num_experts % n:
        raise ValueError(f"{cfg.num_experts} experts not divisible by expert={n}")
    fn = jax.shard_map(
        functools.partial(_moe_local, cfg=cfg, axis=AXIS_EXPERT),
        mesh=mesh,
        in_specs=(moe_pspecs(), P()),
        out_specs=P(),
    )
    return fn(params, x)