"""Mixture-of-Experts FFN with expert parallelism (the `expert` mesh axis).

Completes the parallelism inventory (SURVEY §2.4 EP row: "only if MoE models
are added; GSPMD `expert` axis"). Expert weights carry a leading [E, ...]
axis sharded over ``expert``. Two formulations share that layout:

- **soft routing** (``moe_ffn`` / ``impl="dense"``): every expert computes
  for every token, a top-k-masked softmax weights the outputs. Exact (no
  token ever dropped) but pays E/top_k× the FFN FLOPs — the exactness
  oracle.
- **capacity-based sparse dispatch** (``moe_ffn_sparse`` / ``impl="sparse"``):
  GShard-style static-shape scatter dispatch. Each token's top-k expert
  choices are scattered into a per-expert ``[E, capacity, D]`` buffer
  (token-major priority: earlier tokens win slots), experts run their FFN on
  only their buffer, and a gather+weighted-sum combines. FFN FLOPs are
  ``E * capacity ≈ N * top_k * capacity_factor`` — proportional to top_k,
  not num_experts. Tokens beyond an expert's capacity lose that expert's
  contribution (the standard trade; ``capacity_factor`` sizes the headroom,
  and agreement with soft routing is exact whenever nothing drops).

Everything is static-shape scatter/gather — no data-dependent shapes — so
XLA tiles the expert einsums onto the MXU unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from agentfield_tpu.parallel.mesh import AXIS_EXPERT


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    expert_intermediate: int
    num_experts: int
    top_k: int = 2  # router mass concentrates on k experts (soft weights)


def init_moe_params(cfg: MoEConfig, key: jax.Array, dtype=jnp.float32) -> dict[str, Any]:
    ks = jax.random.split(key, 3)
    d, f, e = cfg.hidden_size, cfg.expert_intermediate, cfg.num_experts
    scale = 0.02
    return {
        "router": (jax.random.normal(ks[0], (d, e), jnp.float32) * scale).astype(dtype),
        "w_in": (jax.random.normal(ks[1], (e, d, f), jnp.float32) * scale).astype(dtype),
        "w_out": (jax.random.normal(ks[2], (e, f, d), jnp.float32) * scale).astype(dtype),
    }


def moe_pspecs() -> dict[str, P]:
    ex = AXIS_EXPERT
    return {"router": P(None, None), "w_in": P(ex, None, None), "w_out": P(ex, None, None)}


def moe_ffn(params: dict[str, Any], cfg: MoEConfig, x: jax.Array) -> jax.Array:
    """Reference (single-device) computation. x: [B, S, D] → [B, S, D]."""
    logits = (x @ params["router"]).astype(jnp.float32)  # [B, S, E]
    mask = topk_router_weights(logits, cfg.top_k)
    h = jnp.einsum("bsd,edf->besf", x, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("besf,efd->besd", h, params["w_out"])
    return jnp.einsum("besd,bse->bsd", y.astype(jnp.float32), mask).astype(x.dtype)


def topk_router_weights(logits: jax.Array, k: int) -> jax.Array:
    """[..., S, E] router logits → [..., S, E] routing weights: softmax over
    the top-k experts' logits, zero elsewhere (exactly HF Mixtral's
    softmax→top-k→renormalize). The ONE routing definition — serving
    (llama._moe_mlp), the dense reference (moe_ffn), and the EP shard body
    (_moe_local) all call it."""
    top, idx = jax.lax.top_k(logits, k)
    batch_idx = jnp.meshgrid(
        *[jnp.arange(n) for n in logits.shape[:-1]], indexing="ij"
    )
    return jnp.zeros_like(logits).at[
        tuple(b[..., None] for b in batch_idx) + (idx,)
    ].set(jax.nn.softmax(top, axis=-1))


def expert_capacity(
    num_tokens: int, num_experts: int, top_k: int, capacity_factor: float
) -> int:
    """Per-expert slot count for sparse dispatch. Static (derived from the
    traced shape), never below top_k so a tiny batch still routes."""
    return max(top_k, math.ceil(num_tokens * top_k / num_experts * capacity_factor))


def sparse_plan(
    logits: jax.Array, k: int, capacity: int, valid: jax.Array | None = None
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """[N, E] router logits → token-major dispatch plan.

    Returns ``(experts, slots, keep, weights)``, each ``[N*k]`` (entry
    ``m`` is token ``m // k``'s choice ``m % k``): the chosen expert id,
    the token's slot within that expert's capacity buffer (its rank among
    earlier entries choosing the same expert — earlier tokens win),
    whether the slot fits under ``capacity``, and the softmax routing
    weight (identical to :func:`topk_router_weights`' nonzeros).

    ``valid`` ([N] bool) excludes tokens from dispatch entirely — they
    occupy no capacity and combine to zero. Serving prefills pass the
    in-range mask: bucket PADDING tokens all share one hidden state, so
    unexcluded they would pile onto the same top-k experts and (token-major)
    starve real tokens behind them out of capacity."""
    n, e_total = logits.shape
    top, idx = jax.lax.top_k(logits, k)  # [N, k]
    weights = jax.nn.softmax(top, axis=-1)
    experts = idx.reshape(-1)  # [M]
    if valid is not None:
        # Invalid entries route "nowhere": expert id E is out of range, so
        # the one-hot row is zero (no rank consumed), the scatter drops it,
        # and `keep` masks it out of the combine.
        experts = jnp.where(jnp.repeat(valid, k), experts, e_total)
    onehot = jax.nn.one_hot(experts, e_total, dtype=jnp.int32)  # [M, E]
    ranks = jnp.cumsum(onehot, axis=0) - onehot  # rank within each expert
    slots = jnp.take_along_axis(
        ranks, jnp.minimum(experts, e_total - 1)[:, None], axis=1
    )[:, 0]
    keep = (slots < capacity) & (experts < e_total)
    return experts, slots, keep, weights.reshape(-1)


def dispatch_tokens(
    xt: jax.Array, experts: jax.Array, slots: jax.Array, num_experts: int, capacity: int
) -> jax.Array:
    """Scatter [N, D] tokens into the [E, C, D] per-expert buffers.
    Over-capacity entries have ``slots >= capacity`` and are dropped by the
    scatter's out-of-bounds mode — no mask needed here."""
    k = experts.shape[0] // xt.shape[0]
    x_rep = jnp.repeat(xt, k, axis=0)  # [M, D]
    buf = jnp.zeros((num_experts, capacity, xt.shape[-1]), xt.dtype)
    return buf.at[experts, slots].set(x_rep, mode="drop")


def combine_tokens(
    y: jax.Array,
    experts: jax.Array,
    slots: jax.Array,
    keep: jax.Array,
    weights: jax.Array,
    k: int,
) -> jax.Array:
    """Gather [E, C, D] expert outputs back to tokens and weight-sum the k
    choices: [N, D] (float32 accumulation)."""
    ec = jnp.minimum(experts, y.shape[0] - 1)
    sc = jnp.minimum(slots, y.shape[1] - 1)
    ym = y[ec, sc].astype(jnp.float32) * (weights * keep)[:, None]
    return ym.reshape(-1, k, y.shape[-1]).sum(axis=1)


def moe_ffn_sparse(
    params: dict[str, Any],
    cfg: MoEConfig,
    x: jax.Array,
    capacity_factor: float = 2.0,
    capacity: int | None = None,
) -> jax.Array:
    """Capacity-based sparse-dispatch MoE FFN (single device). x: [B, S, D].
    Matches :func:`moe_ffn` exactly whenever no expert overflows capacity."""
    b, s, d = x.shape
    n = b * s
    if capacity is None:
        capacity = expert_capacity(n, cfg.num_experts, cfg.top_k, capacity_factor)
    xt = x.reshape(n, d)
    logits = (xt @ params["router"]).astype(jnp.float32)  # [N, E]
    experts, slots, keep, weights = sparse_plan(logits, cfg.top_k, capacity)
    buf = dispatch_tokens(xt, experts, slots, cfg.num_experts, capacity)
    h = jnp.einsum("ecd,edf->ecf", buf, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    out = combine_tokens(y, experts, slots, keep, weights, cfg.top_k)
    return out.reshape(b, s, d).astype(x.dtype)


def _moe_local(params, x, cfg: MoEConfig, axis: str):
    """Per-device body: my expert shard computes for ALL tokens; the router
    (replicated) masks non-resident experts' weights to zero and a psum
    combines across the expert axis."""
    e_local = params["w_in"].shape[0]
    my_idx = jax.lax.axis_index(axis)
    logits = (x @ params["router"]).astype(jnp.float32)  # [B, S, E_total]
    weights = topk_router_weights(logits, cfg.top_k)
    # Slice my experts' routing weights: experts [my_idx*e_local, ...).
    my_w = jax.lax.dynamic_slice_in_dim(weights, my_idx * e_local, e_local, axis=2)
    h = jnp.einsum("bsd,edf->besf", x, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("besf,efd->besd", h, params["w_out"])
    mine = jnp.einsum("besd,bse->bsd", y.astype(jnp.float32), my_w)
    return jax.lax.psum(mine, axis).astype(x.dtype)


def _moe_local_sparse(params, x, cfg: MoEConfig, axis: str, capacity: int):
    """Per-device sparse body: routing (replicated router, all tokens) runs
    on every device; each device scatters only the entries routed to its
    RESIDENT expert shard into a local [E_local, C, D] buffer, computes, and
    combines — the psum sums disjoint expert contributions, so the collective
    cost is identical to soft routing while compute drops to capacity."""
    e_local = params["w_in"].shape[0]
    lo = jax.lax.axis_index(axis) * e_local
    b, s, d = x.shape
    xt = x.reshape(-1, d)
    logits = (xt @ params["router"]).astype(jnp.float32)
    experts, slots, keep, weights = sparse_plan(logits, cfg.top_k, capacity)
    # Local re-index: non-resident entries map to E_local (out of bounds →
    # dropped by the scatter, masked in the combine).
    mine = keep & (experts >= lo) & (experts < lo + e_local)
    experts_loc = jnp.where(mine, experts - lo, e_local)
    buf = dispatch_tokens(xt, experts_loc, slots, e_local, capacity)
    h = jnp.einsum("ecd,edf->ecf", buf, params["w_in"])
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x.dtype)
    y = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    out = combine_tokens(y, experts_loc, slots, mine, weights, cfg.top_k)
    return jax.lax.psum(out, axis).reshape(b, s, d).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "impl", "capacity_factor"))
def moe_ffn_sharded(
    params: dict[str, Any],
    cfg: MoEConfig,
    x: jax.Array,
    mesh: Mesh,
    impl: str = "dense",
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Expert-parallel MoE FFN over the `expert` mesh axis.

    ``impl="dense"`` soft-routes (exact); ``impl="sparse"`` runs the
    capacity-based dispatch (FLOPs ∝ top_k, token-major drop priority —
    identical across devices since routing is computed from replicated
    inputs everywhere)."""
    n = mesh.shape[AXIS_EXPERT]
    if cfg.num_experts % n:
        raise ValueError(f"{cfg.num_experts} experts not divisible by expert={n}")
    if impl == "sparse":
        capacity = expert_capacity(
            x.shape[0] * x.shape[1], cfg.num_experts, cfg.top_k, capacity_factor
        )
        body = functools.partial(
            _moe_local_sparse, cfg=cfg, axis=AXIS_EXPERT, capacity=capacity
        )
    elif impl == "dense":
        body = functools.partial(_moe_local, cfg=cfg, axis=AXIS_EXPERT)
    else:
        raise ValueError(f"impl={impl!r} must be 'dense' or 'sparse'")
    from agentfield_tpu.parallel.mesh import shard_map as shard_map_compat

    fn = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(moe_pspecs(), P()),
        out_specs=P(),
    )
    return fn(params, x)