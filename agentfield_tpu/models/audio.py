"""Audio: log-mel encoder tower (input modality) + TTS head (output modality).

The reference serves audio through external providers — transcription rides
chat parts and TTS/chat-audio hit speech APIs (sdk/python/agentfield/
agent_ai.py:750-1002). Here both directions are SERVED in-tree:

- INPUT — ``audio_encode``: waveform → log-mel spectrogram → frame-grouped
  transformer encoder → LLM-space embeddings, injected at ``<audio>`` marker
  positions of the prompt exactly like the vision tower's patches
  (models/vision.py, LLaVA-style early fusion). The engine's ``mm_embeds``
  seam is modality-agnostic, so audio rides the same injection path.
- OUTPUT — ``tts_synthesize``: byte-level text → transformer encoder →
  per-character frame upsampling → waveform head. With trained weights this
  is a compact non-autoregressive TTS (FastSpeech-family shape); with random
  init it proves the served-output seam end to end (WAV bytes leave ai()).

TPU-first: framing/grouping are reshapes where possible, the mel filterbank
is a constant matmul, encoders are one ``lax.scan`` over stacked layer
weights (models/llama.py idiom), everything lands on the MXU in bf16, and
all shapes are static per config so serving stays compile-friendly.
"""

from __future__ import annotations

import dataclasses
import functools
import io
import struct
import wave as _wave
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AudioConfig:
    """Input tower: waveform → LLM-space embeddings."""

    sample_rate: int = 16000
    n_fft: int = 400  # 25 ms window
    hop: int = 160  # 10 ms hop
    n_mels: int = 80
    max_seconds: float = 10.0  # static waveform budget (pad/trim)
    frame_group: int = 4  # consecutive mel frames per encoder token
    hidden_size: int = 512
    num_layers: int = 6
    num_heads: int = 8
    mlp_ratio: int = 4
    out_dim: int = 2048  # LLM hidden size the projector maps into
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    frontend: str = "group"  # "group": frame_group mel frames → one linear
    # token (compact, train-from-scratch). "conv": Whisper's two-Conv1d stem
    # (k=3; stride 1 then conv_stride) — the layout pretrained Whisper
    # encoders load into (load_whisper_encoder).
    conv_stride: int = 2
    mel_impl: str = "htk"  # "htk": this file's filterbank on raw frames.
    # "whisper": slaney-normalized filters, reflect-padded centered frames,
    # log10 + per-clip max-8 floor + (x+4)/4 — bit-matches
    # WhisperFeatureExtractor so pretrained conv stems see their training
    # distribution.
    gelu_exact: bool = False  # erf gelu (HF "gelu") instead of tanh approx

    @property
    def max_samples(self) -> int:
        return int(self.sample_rate * self.max_seconds)

    @property
    def n_frames(self) -> int:
        if self.mel_impl == "whisper":
            # centered frames (reflect pad n_fft//2 each side), last dropped
            return self.max_samples // self.hop
        return 1 + (self.max_samples - self.n_fft) // self.hop

    @property
    def n_tokens(self) -> int:
        if self.frontend == "conv":
            # conv1 stride 1 (same), conv2 stride conv_stride (same padding)
            return -(-self.n_frames // self.conv_stride)
        return self.n_frames // self.frame_group


@dataclasses.dataclass(frozen=True)
class TTSConfig:
    """Output head: byte-level text → waveform."""

    sample_rate: int = 16000
    vocab_size: int = 256  # byte-level input (self-contained, any tokenizer)
    max_chars: int = 256  # static text budget
    frames_per_char: int = 8  # upsampling factor (≈ phoneme duration)
    samples_per_frame: int = 160  # 10 ms of audio per frame
    hidden_size: int = 384
    num_layers: int = 4
    num_heads: int = 6
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def max_samples(self) -> int:
        return self.max_chars * self.frames_per_char * self.samples_per_frame


CONFIGS = {
    # capacity tower for the flagship 1B preset (Whisper-base-ish encoder)
    "audio-base": AudioConfig(),
    # hermetic test tower: ~1 s budget, compiles in seconds on CPU; out_dim
    # matches llama-tiny's hidden_size so engine tests fuse without adapters
    "audio-tiny": AudioConfig(
        n_fft=128, hop=64, n_mels=16, max_seconds=1.0, frame_group=4,
        hidden_size=32, num_layers=2, num_heads=2, out_dim=128,
    ),
    # openai/whisper-tiny encoder shape (load_whisper_encoder fills the
    # exact dims from the checkpoint's config.json; this preset documents
    # the family and serves random-init smoke tests)
    "whisper-tiny": AudioConfig(
        max_seconds=30.0, hidden_size=384, num_layers=4, num_heads=6,
        frontend="conv", mel_impl="whisper", gelu_exact=True,
        dtype="float32",
    ),
}

TTS_CONFIGS = {
    "tts-base": TTSConfig(),
    # hermetic test head: ~0.5 s ceiling, tiny encoder
    "tts-tiny": TTSConfig(
        max_chars=32, frames_per_char=4, samples_per_frame=40,
        hidden_size=32, num_layers=2, num_heads=2,
    ),
}


def get_audio_config(name: str) -> AudioConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown audio config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]


def get_tts_config(name: str) -> TTSConfig:
    if name not in TTS_CONFIGS:
        raise KeyError(f"unknown tts config {name!r}; have {sorted(TTS_CONFIGS)}")
    return TTS_CONFIGS[name]


# ---------------------------------------------------------------------------
# log-mel front end
# ---------------------------------------------------------------------------


def _mel_filterbank(cfg: AudioConfig) -> np.ndarray:
    """[n_fft//2+1, n_mels] triangular mel filterbank (HTK mel scale).
    Host-built constant — closes into the jitted encoder as a matmul."""
    n_bins = cfg.n_fft // 2 + 1
    f_max = cfg.sample_rate / 2.0
    mel_max = 2595.0 * np.log10(1.0 + f_max / 700.0)
    mel_pts = np.linspace(0.0, mel_max, cfg.n_mels + 2)
    hz_pts = 700.0 * (10.0 ** (mel_pts / 2595.0) - 1.0)
    bins = np.floor((cfg.n_fft + 1) * hz_pts / cfg.sample_rate).astype(int)
    fb = np.zeros((n_bins, cfg.n_mels), np.float32)
    for m in range(1, cfg.n_mels + 1):
        lo, c, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, c):
            if c > lo:
                fb[k, m - 1] = (k - lo) / (c - lo)
        for k in range(c, hi):
            if hi > c:
                fb[k, m - 1] = (hi - k) / (hi - c)
    return fb


def _mel_filterbank_slaney(cfg: AudioConfig) -> np.ndarray:
    """[n_fft//2+1, n_mels] slaney-scale, slaney-normalized filterbank —
    librosa's default and therefore WhisperFeatureExtractor's (continuous
    triangles over FFT bin frequencies, not floored bins)."""
    n_bins = cfg.n_fft // 2 + 1
    fftfreqs = np.linspace(0.0, cfg.sample_rate / 2.0, n_bins)

    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        mel = f * 3.0 / 200.0  # linear below 1 kHz
        log_reg = f >= 1000.0
        mel = np.where(log_reg, 15.0 + np.log(np.maximum(f, 1e-9) / 1000.0) / (np.log(6.4) / 27.0), mel)
        return mel

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        f = m * 200.0 / 3.0
        log_reg = m >= 15.0
        return np.where(log_reg, 1000.0 * np.exp((np.log(6.4) / 27.0) * (m - 15.0)), f)

    mel_pts = np.linspace(
        hz_to_mel(0.0), hz_to_mel(cfg.sample_rate / 2.0), cfg.n_mels + 2
    )
    hz_pts = mel_to_hz(mel_pts)  # [n_mels + 2]
    fdiff = np.diff(hz_pts)
    ramps = hz_pts[:, None] - fftfreqs[None, :]  # [n_mels+2, n_bins]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))  # [n_mels, n_bins]
    enorm = 2.0 / (hz_pts[2 : cfg.n_mels + 2] - hz_pts[:cfg.n_mels])
    fb *= enorm[:, None]
    return fb.T.astype(np.float32)  # [n_bins, n_mels]


def log_mel(cfg: AudioConfig, wave: jax.Array) -> jax.Array:
    """[B, max_samples] float in [-1, 1] → [B, n_frames, n_mels] log-mel.

    Overlapping frames are one strided gather (static index matrix), the DFT
    is ``jnp.fft.rfft`` over the last axis, and the filterbank is a matmul —
    no Python loops inside jit. mel_impl="whisper" reproduces
    WhisperFeatureExtractor: reflect-padded centered frames, periodic hann,
    slaney filters, log10 with a per-clip max-8 floor, (x+4)/4 scaling."""
    if cfg.mel_impl == "whisper":
        half = cfg.n_fft // 2
        padded = jnp.pad(wave, ((0, 0), (half, half)), mode="reflect")
        # n_frames+1 centered frames; Whisper drops the final one
        idx = (
            np.arange(cfg.n_frames + 1)[:, None] * cfg.hop
            + np.arange(cfg.n_fft)[None, :]
        )
        frames = padded[:, idx]  # [B, n_frames+1, n_fft]
        n = np.arange(cfg.n_fft, dtype=np.float32)
        window = jnp.asarray(0.5 * (1.0 - np.cos(2.0 * np.pi * n / cfg.n_fft)))
        spec = jnp.fft.rfft(frames.astype(jnp.float32) * window, axis=-1)
        power = (jnp.abs(spec) ** 2)[:, :-1]  # [B, n_frames, n_bins]
        mel = power @ jnp.asarray(_mel_filterbank_slaney(cfg))
        log_spec = jnp.log10(jnp.maximum(mel, 1e-10))
        peak = jnp.max(log_spec, axis=(1, 2), keepdims=True)
        log_spec = jnp.maximum(log_spec, peak - 8.0)
        return (log_spec + 4.0) / 4.0
    idx = (
        np.arange(cfg.n_frames)[:, None] * cfg.hop + np.arange(cfg.n_fft)[None, :]
    )  # [n_frames, n_fft] static
    frames = wave[:, idx]  # [B, n_frames, n_fft]
    window = jnp.asarray(np.hanning(cfg.n_fft).astype(np.float32))
    spec = jnp.fft.rfft(frames.astype(jnp.float32) * window, axis=-1)
    power = jnp.abs(spec) ** 2
    mel = power @ jnp.asarray(_mel_filterbank(cfg))
    return jnp.log(mel + 1e-6)


# ---------------------------------------------------------------------------
# shared transformer encoder (scan over stacked layers, vision.py idiom)
# ---------------------------------------------------------------------------


def _layer_norm(x: jax.Array, w: jax.Array, b: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _init_encoder_layers(key: jax.Array, L: int, d: int, f: int, dt) -> Params:
    ks = jax.random.split(key, 4)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "ln1_w": jnp.ones((L, d), dt),
        "ln1_b": jnp.zeros((L, d), dt),
        "ln2_w": jnp.ones((L, d), dt),
        "ln2_b": jnp.zeros((L, d), dt),
        "wqkv": norm(ks[0], (L, d, 3 * d)),
        "bqkv": jnp.zeros((L, 3 * d), dt),  # Whisper: q/v biased, k zero
        "wo": norm(ks[1], (L, d, d)),
        "bo": jnp.zeros((L, d), dt),
        "w1": norm(ks[2], (L, d, f)),
        "b1": jnp.zeros((L, f), dt),
        "w2": norm(ks[3], (L, f, d)),
        "b2": jnp.zeros((L, d), dt),
    }


def _encoder(
    x: jax.Array, layers: Params, num_heads: int, eps: float,
    gelu_exact: bool = False,
) -> jax.Array:
    """Bidirectional pre-LN transformer over [B, N, d]; one lax.scan."""
    B, N, d = x.shape
    hd = d // num_heads
    act = functools.partial(jax.nn.gelu, approximate=not gelu_exact)
    # Bias keys arrived with Whisper support; tower checkpoints saved before
    # then upgrade in place to zero biases (identity) instead of KeyError-ing.
    if "bqkv" not in layers:
        L = layers["wqkv"].shape[0]
        f = layers["w1"].shape[-1]
        dt = layers["wqkv"].dtype
        layers = {
            **layers,
            "bqkv": jnp.zeros((L, 3 * d), dt),
            "bo": jnp.zeros((L, d), dt),
            "b1": jnp.zeros((L, f), dt),
            "b2": jnp.zeros((L, d), dt),
        }

    def body(x, lp):
        h = _layer_norm(x, lp["ln1_w"], lp["ln1_b"], eps)
        qkv = (h @ lp["wqkv"] + lp["bqkv"]).reshape(B, N, 3, num_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum(
            "bnhd,bmhd->bhnm", q, k, preferred_element_type=jnp.float32
        ) * (hd**-0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        x = x + (attn.reshape(B, N, d) @ lp["wo"] + lp["bo"])
        h = _layer_norm(x, lp["ln2_w"], lp["ln2_b"], eps)
        up = act((h @ lp["w1"] + lp["b1"]).astype(jnp.float32)).astype(x.dtype)
        x = x + (up @ lp["w2"] + lp["b2"])
        return x, None

    x, _ = jax.lax.scan(body, x, layers)
    return x


# ---------------------------------------------------------------------------
# input tower: waveform → LLM-space embeddings
# ---------------------------------------------------------------------------


def init_audio_params(cfg: AudioConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d = cfg.hidden_size
    keys = jax.random.split(key, 6)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    stem = (
        {
            # Whisper conv stem: [out, in, k] (lax OIH layout)
            "conv1_w": norm(keys[0], (d, cfg.n_mels, 3)),
            "conv1_b": jnp.zeros((d,), dt),
            "conv2_w": norm(keys[5], (d, d, 3)),
            "conv2_b": jnp.zeros((d,), dt),
        }
        if cfg.frontend == "conv"
        else {"frame_embed": norm(keys[0], (cfg.frame_group * cfg.n_mels, d))}
    )
    return {
        **stem,
        "pos_embed": norm(keys[1], (cfg.n_tokens, d)),
        "layers": _init_encoder_layers(keys[2], cfg.num_layers, d, d * cfg.mlp_ratio, dt),
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # two-layer GELU projector into LLM space (vision.py idiom)
        "proj_w1": norm(keys[3], (d, cfg.out_dim)),
        "proj_w2": norm(keys[4], (cfg.out_dim, cfg.out_dim)),
    }


def audio_encode(params: Params, cfg: AudioConfig, wave: jax.Array) -> jax.Array:
    """Encode waveforms into LLM-space embeddings.

    wave: [B, max_samples] float32 in [-1, 1] (pad/trim on host)
    returns: [B, n_tokens, out_dim] in the tower dtype
    """
    dt = jnp.dtype(cfg.dtype)
    mel = log_mel(cfg, wave)  # [B, n_frames, n_mels]
    B = mel.shape[0]
    x = encode_hidden(params, cfg, mel.astype(dt))
    h = jax.nn.gelu((x @ params["proj_w1"]).astype(jnp.float32)).astype(x.dtype)
    return h @ params["proj_w2"]


def encode_hidden(params: Params, cfg: AudioConfig, mel: jax.Array) -> jax.Array:
    """[B, n_frames, n_mels] mel → [B, n_tokens, hidden] encoder states
    (pre-projector; for a Whisper checkpoint these match the HF encoder's
    last_hidden_state)."""
    B = mel.shape[0]
    if cfg.frontend == "conv":
        act = functools.partial(jax.nn.gelu, approximate=not cfg.gelu_exact)
        xc = jnp.transpose(mel, (0, 2, 1))  # [B, n_mels, T]
        dn = ("NCH", "OIH", "NCH")
        # explicit symmetric pad (torch Conv1d padding=1): lax "SAME" puts
        # the stride-2 leftover pad on the right only, shifting every window
        xc = act(
            jax.lax.conv_general_dilated(
                xc.astype(jnp.float32), params["conv1_w"].astype(jnp.float32),
                window_strides=(1,), padding=[(1, 1)], dimension_numbers=dn,
            )
            + params["conv1_b"].astype(jnp.float32)[None, :, None]
        )
        xc = act(
            jax.lax.conv_general_dilated(
                xc, params["conv2_w"].astype(jnp.float32),
                window_strides=(cfg.conv_stride,), padding=[(1, 1)],
                dimension_numbers=dn,
            )
            + params["conv2_b"].astype(jnp.float32)[None, :, None]
        )
        x = jnp.transpose(xc, (0, 2, 1)).astype(mel.dtype)  # [B, n_tokens, d]
        x = x + params["pos_embed"]
    else:
        # group consecutive frames into one token — a reshape, no conv
        usable = cfg.n_tokens * cfg.frame_group
        x = mel[:, :usable].reshape(B, cfg.n_tokens, cfg.frame_group * cfg.n_mels)
        x = x @ params["frame_embed"] + params["pos_embed"]
    x = _encoder(
        x, params["layers"], cfg.num_heads, cfg.layer_norm_eps,
        gelu_exact=cfg.gelu_exact,
    )
    return _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)


audio_encode_jit = jax.jit(audio_encode, static_argnames=("cfg",))


# ---------------------------------------------------------------------------
# pretrained Whisper encoder loading
# ---------------------------------------------------------------------------


def load_whisper_encoder(
    path: str, out_dim: int = 2048, dtype: str = "float32", key=None
) -> tuple[AudioConfig, Params]:
    """HF Whisper checkpoint directory → (AudioConfig, params) for this
    tower: the ENCODER weights load exactly (conv stem, sinusoidal
    positions, stacked attention/MLP layers, final LN — verified against
    transformers' last_hidden_state by tests), while the LLM-space projector
    stays random-init (the multimodal adapter has no Whisper counterpart;
    it is the part a fusion finetune trains, as in LLaVA-style systems).

    Reference capability: audio parts ride external providers
    (sdk/python/agentfield/agent_ai.py:750-1002); here the encoder runs
    in-tree with real pretrained weights.
    """
    import json
    from pathlib import Path as _Path

    from safetensors import safe_open

    p = _Path(path)
    hf = json.loads((p / "config.json").read_text())
    d = int(hf["d_model"])
    cfg = AudioConfig(
        sample_rate=16000,
        n_fft=400,
        hop=160,
        n_mels=int(hf["num_mel_bins"]),
        max_seconds=float(hf.get("max_source_positions", 1500) * 2 * 160) / 16000.0,
        hidden_size=d,
        num_layers=int(hf["encoder_layers"]),
        num_heads=int(hf["encoder_attention_heads"]),
        mlp_ratio=int(hf["encoder_ffn_dim"]) // d,
        out_dim=out_dim,
        frontend="conv",
        mel_impl="whisper",
        gelu_exact=hf.get("activation_function", "gelu") == "gelu",
        dtype=dtype,
    )
    tensors: dict[str, np.ndarray] = {}
    found_any = False
    for f in sorted(p.glob("*.safetensors")):
        found_any = True
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                # only the encoder is used — skip decoder/proj_out shards
                # (half of whisper-large's ~3 GB otherwise loads for nothing)
                if name.startswith(("model.encoder.", "encoder.")):
                    tensors[name] = sf.get_tensor(name)
    if not found_any:
        raise FileNotFoundError(f"no *.safetensors under {p}")
    if not tensors:
        raise KeyError(f"no encoder tensors in {p} (not a Whisper checkpoint?)")

    def get(name: str) -> np.ndarray:
        for prefix in ("model.encoder.", "encoder."):
            if prefix + name in tensors:
                return tensors[prefix + name]
        raise KeyError(f"missing encoder tensor {name!r}")

    dt = jnp.dtype(dtype)
    L = cfg.num_layers

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        mats = [get(fmt.format(i)) for i in range(L)]
        out = np.stack([m.T if transpose else m for m in mats])
        return jnp.asarray(out, dt)

    # qkv: HF stores q/k/v separately; k has NO bias (Whisper convention)
    wq = stack("layers.{}.self_attn.q_proj.weight")
    wk = stack("layers.{}.self_attn.k_proj.weight")
    wv = stack("layers.{}.self_attn.v_proj.weight")
    bq = stack("layers.{}.self_attn.q_proj.bias", transpose=False)
    bv = stack("layers.{}.self_attn.v_proj.bias", transpose=False)
    layers = {
        "ln1_w": stack("layers.{}.self_attn_layer_norm.weight", transpose=False),
        "ln1_b": stack("layers.{}.self_attn_layer_norm.bias", transpose=False),
        "ln2_w": stack("layers.{}.final_layer_norm.weight", transpose=False),
        "ln2_b": stack("layers.{}.final_layer_norm.bias", transpose=False),
        "wqkv": jnp.concatenate([wq, wk, wv], axis=2),
        "bqkv": jnp.concatenate([bq, jnp.zeros_like(bq), bv], axis=1),
        "wo": stack("layers.{}.self_attn.out_proj.weight"),
        "bo": stack("layers.{}.self_attn.out_proj.bias", transpose=False),
        "w1": stack("layers.{}.fc1.weight"),
        "b1": stack("layers.{}.fc1.bias", transpose=False),
        "w2": stack("layers.{}.fc2.weight"),
        "b2": stack("layers.{}.fc2.bias", transpose=False),
    }
    if key is None:
        key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)

    def rand(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    params: Params = {
        "conv1_w": jnp.asarray(get("conv1.weight"), dt),  # [d, n_mels, 3]
        "conv1_b": jnp.asarray(get("conv1.bias"), dt),
        "conv2_w": jnp.asarray(get("conv2.weight"), dt),
        "conv2_b": jnp.asarray(get("conv2.bias"), dt),
        "pos_embed": jnp.asarray(get("embed_positions.weight"), dt)[: cfg.n_tokens],
        "layers": layers,
        "final_ln_w": jnp.asarray(get("layer_norm.weight"), dt),
        "final_ln_b": jnp.asarray(get("layer_norm.bias"), dt),
        "proj_w1": rand(k1, (d, out_dim)),
        "proj_w2": rand(k2, (out_dim, out_dim)),
    }
    return cfg, params


# ---------------------------------------------------------------------------
# output head: text bytes → waveform
# ---------------------------------------------------------------------------


def init_tts_params(cfg: TTSConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d = cfg.hidden_size
    keys = jax.random.split(key, 5)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "char_embed": norm(keys[0], (cfg.vocab_size, d)),
        "pos_embed": norm(keys[1], (cfg.max_chars, d)),
        "layers": _init_encoder_layers(keys[2], cfg.num_layers, d, d * cfg.mlp_ratio, dt),
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # upsample: one char token → frames_per_char frame vectors
        "up_w": norm(keys[3], (d, cfg.frames_per_char * d)),
        # waveform head: one frame vector → samples_per_frame samples
        "wav_w": norm(keys[4], (d, cfg.samples_per_frame)),
    }


def tts_synthesize(params: Params, cfg: TTSConfig, char_ids: jax.Array) -> jax.Array:
    """Non-autoregressive synthesis: [B, max_chars] int32 byte ids (0-padded)
    → [B, max_samples] float32 waveform in (-1, 1). Trim to the speakable
    length (chars * frames_per_char * samples_per_frame) on the host."""
    B = char_ids.shape[0]
    d = cfg.hidden_size
    x = params["char_embed"][char_ids] + params["pos_embed"]
    x = _encoder(x, params["layers"], cfg.num_heads, cfg.layer_norm_eps)
    x = _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    frames = (x @ params["up_w"]).reshape(B, cfg.max_chars * cfg.frames_per_char, d)
    wav = (frames @ params["wav_w"]).astype(jnp.float32).reshape(B, cfg.max_samples)
    return jnp.tanh(wav)


tts_synthesize_jit = jax.jit(tts_synthesize, static_argnames=("cfg",))


# ---------------------------------------------------------------------------
# WAV codec (host side, stdlib only)
# ---------------------------------------------------------------------------


def wav_to_float(data: bytes, target_rate: int, max_samples: int) -> np.ndarray:
    """Decode a PCM WAV to [max_samples] float32 in [-1, 1]: mono-mix,
    nearest-neighbour resample to target_rate, pad/trim to the static
    budget. Raises ValueError on non-PCM or malformed input."""
    try:
        with _wave.open(io.BytesIO(data), "rb") as w:
            n_ch, width, rate, n_frames = (
                w.getnchannels(), w.getsampwidth(), w.getframerate(), w.getnframes(),
            )
            raw = w.readframes(n_frames)
    except (_wave.Error, EOFError, struct.error) as e:
        raise ValueError(f"not a decodable PCM WAV: {e}") from e
    if width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported PCM sample width {width}")
    if n_ch > 1:
        x = x[: (len(x) // n_ch) * n_ch].reshape(-1, n_ch).mean(axis=1)
    if rate != target_rate and len(x):
        idx = np.clip(
            (np.arange(int(len(x) * target_rate / rate)) * rate / target_rate),
            0, len(x) - 1,
        ).astype(np.int64)
        x = x[idx]
    out = np.zeros((max_samples,), np.float32)
    n = min(len(x), max_samples)
    out[:n] = x[:n]
    return out


def float_to_wav(wave_f32: np.ndarray, rate: int) -> bytes:
    """[-1, 1] float32 → 16-bit mono PCM WAV bytes."""
    pcm = (np.clip(wave_f32, -1.0, 1.0) * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with _wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()
