"""Audio: log-mel encoder tower (input modality) + TTS head (output modality).

The reference serves audio through external providers — transcription rides
chat parts and TTS/chat-audio hit speech APIs (sdk/python/agentfield/
agent_ai.py:750-1002). Here both directions are SERVED in-tree:

- INPUT — ``audio_encode``: waveform → log-mel spectrogram → frame-grouped
  transformer encoder → LLM-space embeddings, injected at ``<audio>`` marker
  positions of the prompt exactly like the vision tower's patches
  (models/vision.py, LLaVA-style early fusion). The engine's ``mm_embeds``
  seam is modality-agnostic, so audio rides the same injection path.
- OUTPUT — ``tts_synthesize``: byte-level text → transformer encoder →
  per-character frame upsampling → waveform head. With trained weights this
  is a compact non-autoregressive TTS (FastSpeech-family shape); with random
  init it proves the served-output seam end to end (WAV bytes leave ai()).

TPU-first: framing/grouping are reshapes where possible, the mel filterbank
is a constant matmul, encoders are one ``lax.scan`` over stacked layer
weights (models/llama.py idiom), everything lands on the MXU in bf16, and
all shapes are static per config so serving stays compile-friendly.
"""

from __future__ import annotations

import dataclasses
import io
import struct
import wave as _wave
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AudioConfig:
    """Input tower: waveform → LLM-space embeddings."""

    sample_rate: int = 16000
    n_fft: int = 400  # 25 ms window
    hop: int = 160  # 10 ms hop
    n_mels: int = 80
    max_seconds: float = 10.0  # static waveform budget (pad/trim)
    frame_group: int = 4  # consecutive mel frames per encoder token
    hidden_size: int = 512
    num_layers: int = 6
    num_heads: int = 8
    mlp_ratio: int = 4
    out_dim: int = 2048  # LLM hidden size the projector maps into
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def max_samples(self) -> int:
        return int(self.sample_rate * self.max_seconds)

    @property
    def n_frames(self) -> int:
        return 1 + (self.max_samples - self.n_fft) // self.hop

    @property
    def n_tokens(self) -> int:
        return self.n_frames // self.frame_group


@dataclasses.dataclass(frozen=True)
class TTSConfig:
    """Output head: byte-level text → waveform."""

    sample_rate: int = 16000
    vocab_size: int = 256  # byte-level input (self-contained, any tokenizer)
    max_chars: int = 256  # static text budget
    frames_per_char: int = 8  # upsampling factor (≈ phoneme duration)
    samples_per_frame: int = 160  # 10 ms of audio per frame
    hidden_size: int = 384
    num_layers: int = 4
    num_heads: int = 6
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def max_samples(self) -> int:
        return self.max_chars * self.frames_per_char * self.samples_per_frame


CONFIGS = {
    # capacity tower for the flagship 1B preset (Whisper-base-ish encoder)
    "audio-base": AudioConfig(),
    # hermetic test tower: ~1 s budget, compiles in seconds on CPU; out_dim
    # matches llama-tiny's hidden_size so engine tests fuse without adapters
    "audio-tiny": AudioConfig(
        n_fft=128, hop=64, n_mels=16, max_seconds=1.0, frame_group=4,
        hidden_size=32, num_layers=2, num_heads=2, out_dim=128,
    ),
}

TTS_CONFIGS = {
    "tts-base": TTSConfig(),
    # hermetic test head: ~0.5 s ceiling, tiny encoder
    "tts-tiny": TTSConfig(
        max_chars=32, frames_per_char=4, samples_per_frame=40,
        hidden_size=32, num_layers=2, num_heads=2,
    ),
}


def get_audio_config(name: str) -> AudioConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown audio config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]


def get_tts_config(name: str) -> TTSConfig:
    if name not in TTS_CONFIGS:
        raise KeyError(f"unknown tts config {name!r}; have {sorted(TTS_CONFIGS)}")
    return TTS_CONFIGS[name]


# ---------------------------------------------------------------------------
# log-mel front end
# ---------------------------------------------------------------------------


def _mel_filterbank(cfg: AudioConfig) -> np.ndarray:
    """[n_fft//2+1, n_mels] triangular mel filterbank (HTK mel scale).
    Host-built constant — closes into the jitted encoder as a matmul."""
    n_bins = cfg.n_fft // 2 + 1
    f_max = cfg.sample_rate / 2.0
    mel_max = 2595.0 * np.log10(1.0 + f_max / 700.0)
    mel_pts = np.linspace(0.0, mel_max, cfg.n_mels + 2)
    hz_pts = 700.0 * (10.0 ** (mel_pts / 2595.0) - 1.0)
    bins = np.floor((cfg.n_fft + 1) * hz_pts / cfg.sample_rate).astype(int)
    fb = np.zeros((n_bins, cfg.n_mels), np.float32)
    for m in range(1, cfg.n_mels + 1):
        lo, c, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, c):
            if c > lo:
                fb[k, m - 1] = (k - lo) / (c - lo)
        for k in range(c, hi):
            if hi > c:
                fb[k, m - 1] = (hi - k) / (hi - c)
    return fb


def log_mel(cfg: AudioConfig, wave: jax.Array) -> jax.Array:
    """[B, max_samples] float in [-1, 1] → [B, n_frames, n_mels] log-mel.

    Overlapping frames are one strided gather (static index matrix), the DFT
    is ``jnp.fft.rfft`` over the last axis, and the filterbank is a matmul —
    no Python loops inside jit."""
    idx = (
        np.arange(cfg.n_frames)[:, None] * cfg.hop + np.arange(cfg.n_fft)[None, :]
    )  # [n_frames, n_fft] static
    frames = wave[:, idx]  # [B, n_frames, n_fft]
    window = jnp.asarray(np.hanning(cfg.n_fft).astype(np.float32))
    spec = jnp.fft.rfft(frames.astype(jnp.float32) * window, axis=-1)
    power = jnp.abs(spec) ** 2
    mel = power @ jnp.asarray(_mel_filterbank(cfg))
    return jnp.log(mel + 1e-6)


# ---------------------------------------------------------------------------
# shared transformer encoder (scan over stacked layers, vision.py idiom)
# ---------------------------------------------------------------------------


def _layer_norm(x: jax.Array, w: jax.Array, b: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _init_encoder_layers(key: jax.Array, L: int, d: int, f: int, dt) -> Params:
    ks = jax.random.split(key, 4)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "ln1_w": jnp.ones((L, d), dt),
        "ln1_b": jnp.zeros((L, d), dt),
        "ln2_w": jnp.ones((L, d), dt),
        "ln2_b": jnp.zeros((L, d), dt),
        "wqkv": norm(ks[0], (L, d, 3 * d)),
        "wo": norm(ks[1], (L, d, d)),
        "w1": norm(ks[2], (L, d, f)),
        "w2": norm(ks[3], (L, f, d)),
    }


def _encoder(x: jax.Array, layers: Params, num_heads: int, eps: float) -> jax.Array:
    """Bidirectional pre-LN transformer over [B, N, d]; one lax.scan."""
    B, N, d = x.shape
    hd = d // num_heads

    def body(x, lp):
        h = _layer_norm(x, lp["ln1_w"], lp["ln1_b"], eps)
        qkv = (h @ lp["wqkv"]).reshape(B, N, 3, num_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum(
            "bnhd,bmhd->bhnm", q, k, preferred_element_type=jnp.float32
        ) * (hd**-0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        x = x + attn.reshape(B, N, d) @ lp["wo"]
        h = _layer_norm(x, lp["ln2_w"], lp["ln2_b"], eps)
        x = x + jax.nn.gelu((h @ lp["w1"]).astype(jnp.float32)).astype(x.dtype) @ lp["w2"]
        return x, None

    x, _ = jax.lax.scan(body, x, layers)
    return x


# ---------------------------------------------------------------------------
# input tower: waveform → LLM-space embeddings
# ---------------------------------------------------------------------------


def init_audio_params(cfg: AudioConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d = cfg.hidden_size
    keys = jax.random.split(key, 5)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "frame_embed": norm(keys[0], (cfg.frame_group * cfg.n_mels, d)),
        "pos_embed": norm(keys[1], (cfg.n_tokens, d)),
        "layers": _init_encoder_layers(keys[2], cfg.num_layers, d, d * cfg.mlp_ratio, dt),
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # two-layer GELU projector into LLM space (vision.py idiom)
        "proj_w1": norm(keys[3], (d, cfg.out_dim)),
        "proj_w2": norm(keys[4], (cfg.out_dim, cfg.out_dim)),
    }


def audio_encode(params: Params, cfg: AudioConfig, wave: jax.Array) -> jax.Array:
    """Encode waveforms into LLM-space embeddings.

    wave: [B, max_samples] float32 in [-1, 1] (pad/trim on host)
    returns: [B, n_tokens, out_dim] in the tower dtype
    """
    dt = jnp.dtype(cfg.dtype)
    mel = log_mel(cfg, wave)  # [B, n_frames, n_mels]
    B = mel.shape[0]
    # group consecutive frames into one token — a reshape, no conv unrolling
    usable = cfg.n_tokens * cfg.frame_group
    x = mel[:, :usable].reshape(B, cfg.n_tokens, cfg.frame_group * cfg.n_mels)
    x = x.astype(dt) @ params["frame_embed"] + params["pos_embed"]
    x = _encoder(x, params["layers"], cfg.num_heads, cfg.layer_norm_eps)
    x = _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    h = jax.nn.gelu((x @ params["proj_w1"]).astype(jnp.float32)).astype(x.dtype)
    return h @ params["proj_w2"]


audio_encode_jit = jax.jit(audio_encode, static_argnames=("cfg",))


# ---------------------------------------------------------------------------
# output head: text bytes → waveform
# ---------------------------------------------------------------------------


def init_tts_params(cfg: TTSConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d = cfg.hidden_size
    keys = jax.random.split(key, 5)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "char_embed": norm(keys[0], (cfg.vocab_size, d)),
        "pos_embed": norm(keys[1], (cfg.max_chars, d)),
        "layers": _init_encoder_layers(keys[2], cfg.num_layers, d, d * cfg.mlp_ratio, dt),
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        # upsample: one char token → frames_per_char frame vectors
        "up_w": norm(keys[3], (d, cfg.frames_per_char * d)),
        # waveform head: one frame vector → samples_per_frame samples
        "wav_w": norm(keys[4], (d, cfg.samples_per_frame)),
    }


def tts_synthesize(params: Params, cfg: TTSConfig, char_ids: jax.Array) -> jax.Array:
    """Non-autoregressive synthesis: [B, max_chars] int32 byte ids (0-padded)
    → [B, max_samples] float32 waveform in (-1, 1). Trim to the speakable
    length (chars * frames_per_char * samples_per_frame) on the host."""
    B = char_ids.shape[0]
    d = cfg.hidden_size
    x = params["char_embed"][char_ids] + params["pos_embed"]
    x = _encoder(x, params["layers"], cfg.num_heads, cfg.layer_norm_eps)
    x = _layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    frames = (x @ params["up_w"]).reshape(B, cfg.max_chars * cfg.frames_per_char, d)
    wav = (frames @ params["wav_w"]).astype(jnp.float32).reshape(B, cfg.max_samples)
    return jnp.tanh(wav)


tts_synthesize_jit = jax.jit(tts_synthesize, static_argnames=("cfg",))


# ---------------------------------------------------------------------------
# WAV codec (host side, stdlib only)
# ---------------------------------------------------------------------------


def wav_to_float(data: bytes, target_rate: int, max_samples: int) -> np.ndarray:
    """Decode a PCM WAV to [max_samples] float32 in [-1, 1]: mono-mix,
    nearest-neighbour resample to target_rate, pad/trim to the static
    budget. Raises ValueError on non-PCM or malformed input."""
    try:
        with _wave.open(io.BytesIO(data), "rb") as w:
            n_ch, width, rate, n_frames = (
                w.getnchannels(), w.getsampwidth(), w.getframerate(), w.getnframes(),
            )
            raw = w.readframes(n_frames)
    except (_wave.Error, EOFError, struct.error) as e:
        raise ValueError(f"not a decodable PCM WAV: {e}") from e
    if width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported PCM sample width {width}")
    if n_ch > 1:
        x = x[: (len(x) // n_ch) * n_ch].reshape(-1, n_ch).mean(axis=1)
    if rate != target_rate and len(x):
        idx = np.clip(
            (np.arange(int(len(x) * target_rate / rate)) * rate / target_rate),
            0, len(x) - 1,
        ).astype(np.int64)
        x = x[idx]
    out = np.zeros((max_samples,), np.float32)
    n = min(len(x), max_samples)
    out[:n] = x[:n]
    return out


def float_to_wav(wave_f32: np.ndarray, rate: int) -> bytes:
    """[-1, 1] float32 → 16-bit mono PCM WAV bytes."""
    pcm = (np.clip(wave_f32, -1.0, 1.0) * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with _wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()
