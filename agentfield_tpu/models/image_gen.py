"""Image generation head: text → image, served through the response-parts seam.

The reference's image generation forwards prompts to provider image APIs
(sdk/python/agentfield/agent_ai.py:1004-1067). Here the modality is SERVED
in-tree, exactly the way the TTS head serves audio output (models/audio.py):
a compact non-autoregressive text-to-canvas model whose PNG bytes ride the
``parts`` response seam. With trained weights this is a small direct
text-to-image decoder (pixel-regression family); with random init it proves
the served-output path end to end — ``ai(output="image")`` returns a
decodable PNG.

TPU-first: byte-level text encoder and canvas decoder are the shared
``lax.scan`` transformer from models/audio.py, the canvas is a learned grid
of patch queries (unpatchify is a reshape — no deconvolutions), all matmuls
land on the MXU in bf16, and every shape is static per config.
"""

from __future__ import annotations

import dataclasses
import io
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu.models.audio import _encoder, _init_encoder_layers, _layer_norm

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ImageGenConfig:
    vocab_size: int = 256  # byte-level prompt (self-contained, any tokenizer)
    max_chars: int = 256  # static text budget
    image_size: int = 64  # square output canvas
    patch_size: int = 8
    hidden_size: int = 384
    num_text_layers: int = 3
    num_canvas_layers: int = 3
    num_heads: int = 6
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


CONFIGS = {
    "imagegen-base": ImageGenConfig(image_size=256, patch_size=16, hidden_size=768,
                                    num_text_layers=6, num_canvas_layers=6, num_heads=12),
    # hermetic test head: 32px canvas, tiny stacks
    "imagegen-tiny": ImageGenConfig(
        max_chars=32, image_size=32, patch_size=8, hidden_size=32,
        num_text_layers=1, num_canvas_layers=1, num_heads=2,
    ),
}


def get_imagegen_config(name: str) -> ImageGenConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown imagegen config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]


def init_imagegen_params(cfg: ImageGenConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    d = cfg.hidden_size
    keys = jax.random.split(key, 6)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "char_embed": norm(keys[0], (cfg.vocab_size, d)),
        "char_pos": norm(keys[1], (cfg.max_chars, d)),
        "text_layers": _init_encoder_layers(keys[2], cfg.num_text_layers, d, d * cfg.mlp_ratio, dt),
        # learned patch queries: the canvas grid, conditioned on pooled text
        "canvas_queries": norm(keys[3], (cfg.num_patches, d)),
        "canvas_layers": _init_encoder_layers(keys[4], cfg.num_canvas_layers, d, d * cfg.mlp_ratio, dt),
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        "patch_head": norm(keys[5], (d, cfg.patch_dim)),
    }


def imagegen_synthesize(params: Params, cfg: ImageGenConfig, char_ids: jax.Array) -> jax.Array:
    """[B, max_chars] int32 byte ids (0-padded) → [B, S, S, 3] float32 in
    (0, 1). Non-autoregressive: encode the text, mean-pool into a
    conditioning vector, add it to every learned canvas query, run the
    canvas decoder, emit patches, unpatchify by reshape."""
    B = char_ids.shape[0]
    d = cfg.hidden_size
    x = params["char_embed"][char_ids] + params["char_pos"]
    x = _encoder(x, params["text_layers"], cfg.num_heads, cfg.layer_norm_eps)
    # masked mean over real (nonzero) chars; all-padding prompts fall back
    # to a plain mean so the conditioning never divides by zero
    real = (char_ids > 0).astype(jnp.float32)[..., None]
    denom = jnp.maximum(real.sum(axis=1), 1.0)
    cond = (x.astype(jnp.float32) * real).sum(axis=1) / denom  # [B, d]
    canvas = params["canvas_queries"][None] + cond[:, None, :].astype(x.dtype)
    canvas = _encoder(canvas, params["canvas_layers"], cfg.num_heads, cfg.layer_norm_eps)
    canvas = _layer_norm(canvas, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    patches = (canvas @ params["patch_head"]).astype(jnp.float32)  # [B, N, pdim]
    g, p = cfg.image_size // cfg.patch_size, cfg.patch_size
    img = patches.reshape(B, g, g, p, p, 3).transpose(0, 1, 3, 2, 4, 5)
    return jax.nn.sigmoid(img.reshape(B, cfg.image_size, cfg.image_size, 3))


imagegen_synthesize_jit = jax.jit(imagegen_synthesize, static_argnames=("cfg",))


def image_to_png(img: np.ndarray) -> bytes:
    """[S, S, 3] float in [0, 1] → PNG bytes (PIL, host side)."""
    from PIL import Image

    arr = (np.clip(np.asarray(img, np.float32), 0.0, 1.0) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()
