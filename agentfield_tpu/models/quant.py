"""Weight-only int8 quantization for serving.

TPU decode is HBM-bandwidth-bound: every decode step streams the full weight
matrix set from HBM into the MXU while activations stay tiny, so halving the
bytes per weight is a direct throughput lever (the reference has no analogue
— its models live behind external providers, agent_ai.py:95-447).

Design:
- **Per-output-channel symmetric int8.** ``w ≈ q * scale`` with
  ``scale[j] = max_i |w[i, j]| / 127``. Because the scale is constant along
  the *contraction* axis, dequantization commutes with the matmul:
  ``x @ (q * s) == (x @ q) * s`` — the kernel multiplies the int8 weights
  straight into the MXU (XLA fuses the int8→bf16 convert into the dot's
  operand read) and applies one [d_out] rescale to the product. The full
  bf16 weight matrix is never materialized.
- **Transparent call sites.** :class:`QuantW` is a pytree node implementing
  ``__rmatmul__``; JAX arrays defer unrecognized ``@`` operands, so
  ``x @ lp["wq"]`` in models/llama.py works unchanged for fp and quantized
  params alike — one forward implementation, no quant branches.
- **Scan/jit/shard compatible.** Both leaves (q [L, in, out] int8,
  scale [L, out] f32) carry the stacked-layer axis, so ``lax.scan`` over
  ``params["layers"]`` slices them in lockstep; parallel/sharding.py maps
  the q spec's output axis onto the scale.

Embeddings and lm_head stay fp: ``jnp.take`` reads only B×S rows (not
bandwidth-bound) and the final projection dominates logit accuracy.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# Weight leaves of models.llama.init_params that carry the decode-step HBM
# traffic; order/keys mirror the init (biases + norms stay fp — trivial bytes).
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@jax.tree_util.register_pytree_node_class
class QuantW:
    """int8 weight + per-output-channel scale behaving like the fp matrix on
    the right side of ``@``."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q  # [..., d_in, d_out] int8
        self.scale = scale  # [..., d_out] f32

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def __rmatmul__(self, x: jax.Array) -> jax.Array:
        # (x @ q) * s == x @ (q * s): scale is constant along the contraction
        # axis. The convert rides the dot's operand read; no dequantized
        # matrix is materialized.
        y = x @ self.q.astype(x.dtype)
        return y * self.scale.astype(y.dtype)

    # dense-mix specs ([.., E, S, out] outputs) + sparse-dispatch buffer
    # specs ([E, C, out] outputs) — both broadcast scale [E, out] as
    # [E, 1, out] against the output's second-to-last axis.
    _EXPERT_SPECS = (
        "bsd,edf->besf", "besf,efd->besd", "ecd,edf->ecf", "ecf,efd->ecd",
    )

    def expert_einsum(self, spec: str, x: jax.Array) -> jax.Array:
        """Quantized MoE expert contraction (``einsum(spec, x, w)`` with the
        weight as the SECOND operand). Same post-contraction rescale trick
        as ``@``: the per-output-channel scale commutes out of the einsum.
        The scale broadcast is layout-specific ([..., E, S, out] outputs), so
        only the specs models/llama._moe_mlp uses are accepted — an
        unanticipated spec must fail loudly, not rescale the wrong axis."""
        if spec not in self._EXPERT_SPECS:
            raise ValueError(
                f"expert_einsum supports {self._EXPERT_SPECS}, got {spec!r}"
            )
        y = jnp.einsum(spec, x, self.q.astype(x.dtype))
        # scale [E, out] broadcasts against y [..., E, S, out]
        return y * self.scale[..., :, None, :].astype(y.dtype)

    def dequantize(self) -> jax.Array:
        """Materialize the fp approximation (tests/debugging only)."""
        return self.q.astype(jnp.float32) * self.scale[..., None, :]

    def __repr__(self):
        return f"QuantW(q={self.q.shape} int8, scale={self.scale.shape})"


def quantize_weight(w: jax.Array) -> QuantW:
    """[..., d_in, d_out] fp → QuantW. Symmetric per-output-channel."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)  # [..., d_out]
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QuantW(q, scale)


def quantize_params(params: dict[str, Any]) -> dict[str, Any]:
    """Quantize the layer-stack weight matrices of a llama param tree
    (models/llama.py init_params layout). Idempotent on already-quantized
    trees; everything outside QUANT_KEYS passes through untouched."""
    out = dict(params)
    layers = dict(params["layers"])
    for k in QUANT_KEYS:
        w = layers.get(k)
        if w is not None and not isinstance(w, QuantW):
            # 3D [L, in, out] dense weights AND 4D [L, E, in, out] MoE expert
            # stacks (per-output-channel scales either way; the MoE einsum
            # dispatches through QuantW.expert_einsum). The router stays fp —
            # trivially small and routing precision matters most.
            layers[k] = quantize_weight(w)
    out["layers"] = layers
    return out


def is_quantized(params: dict[str, Any]) -> bool:
    return any(isinstance(params.get("layers", {}).get(k), QuantW) for k in QUANT_KEYS)
