"""HF Llama checkpoint loading (safetensors → stacked pytree).

The reference never touches weights (models live behind provider APIs); this
is the TPU build's model-ingest path: read a HuggingFace Llama checkpoint
directory (config.json + *.safetensors), emit the stacked-layer param pytree
of ``models.llama`` (projections transposed to [in, out] for x @ w on the
MXU), optionally placing shards straight onto a mesh.

RoPE uses HF's rotate-half convention end to end, so no permutation of q/k
weights is needed (models/llama.py::apply_rope).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu.models.configs import LlamaConfig


def config_from_hf(path: str | Path) -> LlamaConfig:
    doc = json.loads((Path(path) / "config.json").read_text())
    if doc.get("model_type") not in (
        "llama", "mistral", "qwen2", "gemma", "mixtral", "phi3", None
    ):
        raise ValueError(
            f"unsupported model_type={doc.get('model_type')!r} "
            "(llama/mistral/qwen2/gemma/mixtral/phi3)"
        )
    if float(doc.get("partial_rotary_factor", 1.0)) != 1.0:
        raise ValueError(
            "partial_rotary_factor != 1.0 is not implemented; loading would "
            "silently produce wrong logits"
        )
    gemma = doc.get("model_type") == "gemma"
    sliding_window = None
    if doc.get("sliding_window") and doc.get("use_sliding_window", True):
        # (Qwen2 configs carry sliding_window but disable it via
        # use_sliding_window=false — full attention matches the reference.)
        sliding_window = int(doc["sliding_window"])
    rope_scaling = None
    rs = doc.get("rope_scaling")
    if rs:
        kind = rs.get("rope_type", rs.get("type", "default"))
        if kind == "llama3":
            from agentfield_tpu.models.configs import RopeScaling

            rope_scaling = RopeScaling(
                factor=float(rs["factor"]),
                low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
                high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
                original_max_position_embeddings=int(
                    rs.get("original_max_position_embeddings", 8192)
                ),
            )
        elif kind not in ("default", None):
            raise ValueError(
                f"unsupported rope_scaling type {kind!r} (only 'llama3'/'default'); "
                "loading would silently produce wrong logits"
            )
    hidden = doc["hidden_size"]
    heads = doc["num_attention_heads"]
    return LlamaConfig(
        vocab_size=doc["vocab_size"],
        hidden_size=hidden,
        intermediate_size=doc["intermediate_size"],
        num_layers=doc["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=doc.get("num_key_value_heads", heads),
        head_dim=doc.get("head_dim", hidden // heads),
        rope_theta=doc.get("rope_theta", 10000.0),
        rope_scaling=rope_scaling,
        attn_bias=doc.get("attention_bias", doc.get("model_type") == "qwen2"),
        rms_norm_eps=doc.get("rms_norm_eps", 1e-5),
        max_seq_len=doc.get("max_position_embeddings", 8192),
        # HF GemmaConfig defaults tie_word_embeddings=True (often omitted)
        tie_embeddings=doc.get("tie_word_embeddings", gemma),
        # gemma family: GeGLU MLP, x*(1+w) norms, sqrt(d)-scaled embeddings
        mlp_act=_mlp_act_from_hf(doc.get("hidden_act"), gemma),
        norm_offset=gemma,
        scale_embeddings=gemma,
        num_experts=doc.get("num_local_experts", 0),
        num_experts_per_tok=doc.get("num_experts_per_tok", 2),
        sliding_window=sliding_window,
    )


def _mlp_act_from_hf(hidden_act: str | None, gemma: bool) -> str:
    """Exact activation mapping — a near-miss (quick_gelu, erf gelu) must
    fail loudly, not silently compute a different function (same policy as
    the rope_scaling check above)."""
    if hidden_act in (None, "silu", "swish"):
        return "gelu" if gemma else "silu"  # gemma's config default is GeGLU
    if hidden_act in ("gelu_pytorch_tanh", "gelu_tanh"):
        return "gelu"  # jax.nn.gelu's default tanh approximation, exactly
    raise ValueError(
        f"unsupported hidden_act={hidden_act!r} (silu / gelu_pytorch_tanh); "
        "loading would silently produce wrong logits"
    )


def _open_all(path: Path) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    tensors: dict[str, Any] = {}
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for f in files:
        handle = safe_open(str(f), framework="numpy")
        for name in handle.keys():
            tensors[name] = (handle, name)
    return tensors


def load_hf_checkpoint(
    path: str | Path,
    cfg: LlamaConfig | None = None,
    dtype: str = "bfloat16",
) -> tuple[LlamaConfig, Any]:
    """Returns (config, params). Tensors are read lazily per-layer to keep
    peak host memory ~2 layers, cast to `dtype`."""
    path = Path(path)
    if cfg is None:
        cfg = config_from_hf(path)
    handles = _open_all(path)
    dt = jnp.dtype(dtype)

    def get(name: str) -> np.ndarray:
        if name not in handles:
            raise KeyError(f"tensor {name!r} missing from checkpoint {path}")
        handle, key = handles[name]
        return handle.get_tensor(key)

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        per_layer = []
        for i in range(cfg.num_layers):
            t = get(fmt.format(i=i))
            per_layer.append(t.T if transpose else t)
        return jnp.asarray(np.stack(per_layer)).astype(dt)

    def stack_norm(fmt: str) -> jnp.ndarray:
        w = stack(fmt, transpose=False)
        # norm_offset checkpoints store w for x*(1+w); fold the 1.0 here so
        # the runtime rms_norm stays one code path (models/llama.py).
        return w + 1.0 if cfg.norm_offset else w

    def stack_experts(fmt: str) -> jnp.ndarray:
        """Mixtral expert weights → [L, E, in, out] (HF stores [out, in])."""
        per_layer = []
        for i in range(cfg.num_layers):
            per_layer.append(
                np.stack([get(fmt.format(i=i, e=e)).T for e in range(cfg.num_experts)])
            )
        return jnp.asarray(np.stack(per_layer)).astype(dt)

    p = "model.layers.{i}."
    fused_qkv = "model.layers.0.self_attn.qkv_proj.weight" in handles
    fused_mlp = "model.layers.0.mlp.gate_up_proj.weight" in handles

    _fused_cache: dict[str, np.ndarray] = {}

    def stack_split(fmt: str, splits: list[int], part: int) -> jnp.ndarray:
        """Phi-3 fuses projections row-wise ([out, in]); split, then
        transpose into this repo's [in, out] layout. The fused tensor is
        read once and cached until its LAST part is taken (qkv_proj would
        otherwise hit disk 3x per layer — ~[9216, 3072] each on the mini)."""
        last_part = len(splits)
        per_layer = []
        for i in range(cfg.num_layers):
            name = fmt.format(i=i)
            if name not in _fused_cache:
                _fused_cache[name] = get(name)
            per_layer.append(np.split(_fused_cache[name], splits)[part].T)
            if part == last_part:
                del _fused_cache[name]  # keep peak host memory ~1 tensor
        return jnp.asarray(np.stack(per_layer)).astype(dt)

    if cfg.num_experts > 0:
        # Mixtral block_sparse_moe: gate = router, experts.N.w1/w3/w2 =
        # gate/up/down (reference modeling_mixtral naming)
        mlp_params = {
            "router": stack(p + "block_sparse_moe.gate.weight", transpose=True),
            "w_gate": stack_experts(p + "block_sparse_moe.experts.{e}.w1.weight"),
            "w_up": stack_experts(p + "block_sparse_moe.experts.{e}.w3.weight"),
            "w_down": stack_experts(p + "block_sparse_moe.experts.{e}.w2.weight"),
        }
    elif fused_mlp:
        # Phi-3 gate_up_proj: [2f, d] rows = gate then up (modeling_phi3)
        f = cfg.intermediate_size
        mlp_params = {
            "w_gate": stack_split(p + "mlp.gate_up_proj.weight", [f], 0),
            "w_up": stack_split(p + "mlp.gate_up_proj.weight", [f], 1),
            "w_down": stack(p + "mlp.down_proj.weight", transpose=True),
        }
    else:
        mlp_params = {
            "w_gate": stack(p + "mlp.gate_proj.weight", transpose=True),
            "w_up": stack(p + "mlp.up_proj.weight", transpose=True),
            "w_down": stack(p + "mlp.down_proj.weight", transpose=True),
        }
    params: dict[str, Any] = {
        "embed": jnp.asarray(get("model.embed_tokens.weight")).astype(dt),
        "layers": {
            "attn_norm": stack_norm(p + "input_layernorm.weight"),
            "mlp_norm": stack_norm(p + "post_attention_layernorm.weight"),
            # Phi-3 qkv_proj rows: q (q_dim) then k then v (kv_dim each)
            "wq": (
                stack_split(p + "self_attn.qkv_proj.weight",
                            [cfg.q_dim, cfg.q_dim + cfg.kv_dim], 0)
                if fused_qkv
                else stack(p + "self_attn.q_proj.weight", transpose=True)
            ),
            "wk": (
                stack_split(p + "self_attn.qkv_proj.weight",
                            [cfg.q_dim, cfg.q_dim + cfg.kv_dim], 1)
                if fused_qkv
                else stack(p + "self_attn.k_proj.weight", transpose=True)
            ),
            "wv": (
                stack_split(p + "self_attn.qkv_proj.weight",
                            [cfg.q_dim, cfg.q_dim + cfg.kv_dim], 2)
                if fused_qkv
                else stack(p + "self_attn.v_proj.weight", transpose=True)
            ),
            "wo": stack(p + "self_attn.o_proj.weight", transpose=True),
            **mlp_params,
        },
        "final_norm": (
            jnp.asarray(get("model.norm.weight")).astype(dt) + 1.0
            if cfg.norm_offset
            else jnp.asarray(get("model.norm.weight")).astype(dt)
        ),
    }
    if cfg.attn_bias:
        params["layers"]["bq"] = stack(p + "self_attn.q_proj.bias", transpose=False)
        params["layers"]["bk"] = stack(p + "self_attn.k_proj.bias", transpose=False)
        params["layers"]["bv"] = stack(p + "self_attn.v_proj.bias", transpose=False)
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T).astype(dt)
    return cfg, params


def save_hf_checkpoint(path: str | Path, cfg: LlamaConfig, params: Any) -> None:
    """Inverse mapping (for tests and for exporting fine-tuned weights)."""
    from safetensors.numpy import save_file

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    # Inverse of the load-time norm fold: norm_offset checkpoints store w
    # for x*(1+w) while params hold the runtime weight (1+w).
    noff = 1.0 if cfg.norm_offset else 0.0
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32) - noff,
    }
    norm_keys = {"attn_norm", "mlp_norm"}
    names = {
        "attn_norm": ("input_layernorm.weight", False),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    if cfg.attn_bias:
        names["bq"] = ("self_attn.q_proj.bias", False)
        names["bk"] = ("self_attn.k_proj.bias", False)
        names["bv"] = ("self_attn.v_proj.bias", False)
    if cfg.num_experts > 0:
        for k in ("w_gate", "w_up", "w_down"):
            names.pop(k)
        router = np.asarray(params["layers"]["router"], np.float32)
        expert_names = {"w_gate": "w1", "w_up": "w3", "w_down": "w2"}
        # One device→host conversion per stack, NOT per layer (a 8x7B expert
        # stack is ~47 GB in f32; converting it inside the layer loop would
        # multiply that by num_layers).
        expert_stacks = {
            ours: np.asarray(params["layers"][ours], np.float32)
            for ours in expert_names
        }
        for i in range(cfg.num_layers):
            out[f"model.layers.{i}.block_sparse_moe.gate.weight"] = (
                np.ascontiguousarray(router[i].T)
            )
            for ours, theirs in expert_names.items():
                for e in range(cfg.num_experts):
                    out[
                        f"model.layers.{i}.block_sparse_moe.experts.{e}.{theirs}.weight"
                    ] = np.ascontiguousarray(expert_stacks[ours][i, e].T)
    for ours, (theirs, transpose) in names.items():
        stacked = np.asarray(params["layers"][ours], np.float32)
        if ours in norm_keys:
            stacked = stacked - noff
        for i in range(cfg.num_layers):
            t = stacked[i].T if transpose else stacked[i]
            out[f"model.layers.{i}.{theirs}"] = np.ascontiguousarray(t)
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.ascontiguousarray(np.asarray(params["lm_head"], np.float32).T)
    save_file(out, str(path / "model.safetensors"))
    (path / "config.json").write_text(
        json.dumps(
            {
                "model_type": (
                    "gemma" if cfg.norm_offset
                    else "mixtral" if cfg.num_experts > 0
                    else "llama"
                ),
                **(
                    {
                        "num_local_experts": cfg.num_experts,
                        "num_experts_per_tok": cfg.num_experts_per_tok,
                    }
                    if cfg.num_experts > 0
                    else {}
                ),
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "intermediate_size": cfg.intermediate_size,
                "num_hidden_layers": cfg.num_layers,
                "num_attention_heads": cfg.num_heads,
                "num_key_value_heads": cfg.num_kv_heads,
                "head_dim": cfg.head_dim,
                "rope_theta": cfg.rope_theta,
                **(
                    {
                        "rope_scaling": {
                            "rope_type": "llama3",
                            "factor": cfg.rope_scaling.factor,
                            "low_freq_factor": cfg.rope_scaling.low_freq_factor,
                            "high_freq_factor": cfg.rope_scaling.high_freq_factor,
                            "original_max_position_embeddings": cfg.rope_scaling.original_max_position_embeddings,
                        }
                    }
                    if cfg.rope_scaling
                    else {}
                ),
                "rms_norm_eps": cfg.rms_norm_eps,
                "max_position_embeddings": cfg.max_seq_len,
                "tie_word_embeddings": cfg.tie_embeddings,
                "attention_bias": cfg.attn_bias,
                # explicit so a gelu LLAMA-architecture model survives the
                # round trip (gemma-ness alone doesn't encode the activation)
                "hidden_act": (
                    "gelu_pytorch_tanh" if cfg.mlp_act == "gelu" else "silu"
                ),
                **(
                    {"sliding_window": cfg.sliding_window}
                    if cfg.sliding_window is not None
                    else {}
                ),
            }
        )
    )
