"""Functional JAX implementation of the Llama decoder family.

TPU-first design:

- Parameters are a plain pytree with all transformer layers **stacked** on a
  leading axis; the forward pass is a single ``lax.scan`` over layers, so XLA
  compiles one layer body regardless of depth (fast compiles, perfect for
  pjit partitioning and pipeline stages later).
- Every projection is stored ``[in, out]`` so ``x @ w`` lands on the MXU with
  no transposes; softmax/norm accumulation is float32, weights bfloat16.
- No data-dependent Python control flow — everything is jit/scan/pjit safe.

This is the in-tree replacement for the reference's external-provider LLM
path (reference: sdk/python/agentfield/agent_ai.py:95-447 delegates
``Agent.ai()`` to litellm; here the model is local and TPU-resident).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from agentfield_tpu.models.configs import LlamaConfig

Params = dict[str, Any]

_NEG_INF = -1e30  # large-negative instead of -inf: avoids NaN from all-masked rows


def resolve_dtype(name: str) -> jnp.dtype:
    return jnp.dtype(name)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(cfg: LlamaConfig, key: jax.Array, dtype: str | None = None) -> Params:
    """Random-normal init. Layers are stacked on axis 0 of every layer leaf."""
    dt = resolve_dtype(dtype or cfg.dtype)
    d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    L = cfg.num_layers
    keys = jax.random.split(key, 10)

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    scale = 0.02
    E = cfg.num_experts
    if E > 0:  # Mixtral-style MoE FFN: expert axis after the layer stack
        mlp = {
            "router": norm(keys[9], (L, d, E), scale),
            "w_gate": norm(keys[5], (L, E, d, f), scale),
            "w_up": norm(keys[6], (L, E, d, f), scale),
            "w_down": norm(keys[7], (L, E, f, d), scale),
        }
    else:
        mlp = {
            "w_gate": norm(keys[5], (L, d, f), scale),
            "w_up": norm(keys[6], (L, d, f), scale),
            "w_down": norm(keys[7], (L, f, d), scale),
        }
    params: Params = {
        "embed": norm(keys[0], (v, d), scale),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "mlp_norm": jnp.ones((L, d), dt),
            "wq": norm(keys[1], (L, d, cfg.q_dim), scale),
            "wk": norm(keys[2], (L, d, cfg.kv_dim), scale),
            "wv": norm(keys[3], (L, d, cfg.kv_dim), scale),
            "wo": norm(keys[4], (L, cfg.q_dim, d), scale),
            **mlp,
        },
        "final_norm": jnp.ones((d,), dt),
    }
    if cfg.attn_bias:  # Qwen2-style QKV biases
        params["layers"]["bq"] = jnp.zeros((L, cfg.q_dim), dt)
        params["layers"]["bk"] = jnp.zeros((L, cfg.kv_dim), dt)
        params["layers"]["bv"] = jnp.zeros((L, cfg.kv_dim), dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(keys[8], (d, v), scale)
    return params


# ---------------------------------------------------------------------------
# Building blocks (shared with the paged serving engine)
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    # `w` is the RUNTIME weight: for norm_offset (gemma x*(1+w)) checkpoints
    # the 1.0 is folded in at load (hf_loader), keeping one forward path.
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def embed_tokens(params: Params, cfg: LlamaConfig, tokens: jax.Array) -> jax.Array:
    """Token-table lookup; gemma-family configs scale by sqrt(hidden) (the
    tied UNEMBED uses the raw table, so the scale cannot be pre-folded)."""
    x = jnp.take(params["embed"], tokens, axis=0)
    if cfg.scale_embeddings:
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    return x


def rope_sincos(positions: jax.Array, head_dim: int, theta: float, scaling=None):
    """cos/sin tables for the given absolute positions. positions: [...].

    ``scaling`` is an optional :class:`~agentfield_tpu.models.configs.RopeScaling`
    applying Llama-3.1/3.2-style frequency rescaling (HF ``rope_scaling`` with
    ``rope_type="llama3"``): long wavelengths are stretched by ``factor`` with
    a smooth ramp between the high-/low-frequency cutoffs, so 3.1/3.2
    checkpoints produce reference-exact logits at all positions.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling is not None:
        wavelen = 2.0 * jnp.pi / inv_freq
        orig = float(scaling.original_max_position_embeddings)
        low_wl = orig / scaling.low_freq_factor  # longest unscaled wavelength
        high_wl = orig / scaling.high_freq_factor
        smooth = (orig / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        interp = (1.0 - smooth) * inv_freq / scaling.factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen < high_wl, inv_freq, jnp.where(wavelen > low_wl, inv_freq / scaling.factor, interp)
        )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs split at head_dim/2 (HF 'rotate_half' convention, so HF
    checkpoints load without permutation). x: [B, S, N, hd]; cos/sin: [B, S, hd/2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    c, s = cos[..., None, :], sin[..., None, :]  # broadcast over heads
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def attention_ref(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, T, Kh, hd]
    v: jax.Array,  # [B, T, Kh, hd]
    q_pos: jax.Array,  # [B, S] absolute positions of queries
    k_pos: jax.Array,  # [B, T] absolute positions of keys
    k_valid: jax.Array,  # [B, T] bool — is this key slot populated
    window: int | None = None,  # sliding window: keys within the most
    # recent `window` positions of each query (HF Mistral semantics)
) -> jax.Array:
    """Reference GQA attention with causal+validity masking, f32 softmax.

    This is the XLA-fused fallback; the Pallas flash/paged kernels in
    ``agentfield_tpu.ops`` are drop-in replacements on TPU.
    """
    B, S, H, hd = q.shape
    Kh = k.shape[2]
    rep = H // Kh
    qg = q.reshape(B, S, Kh, rep, hd)
    logits = jnp.einsum(
        "bskrh,btkh->bkrst", qg, k, preferred_element_type=jnp.float32
    ) * (hd ** -0.5)
    mask = (k_pos[:, None, :] <= q_pos[:, :, None]) & k_valid[:, None, :]  # [B,S,T]
    if window is not None:
        mask = mask & (k_pos[:, None, :] > q_pos[:, :, None] - window)
    logits = jnp.where(mask[:, None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bkrst,btkh->bskrh", probs, v, preferred_element_type=jnp.float32
    )
    return out.reshape(B, S, H, hd).astype(q.dtype)


def qkv_proj(lp: Params, x_normed: jax.Array, cfg: LlamaConfig, cos, sin):
    """Project (+bias for Qwen2-style configs) + rope.
    Returns q [B,S,H,hd], k/v [B,S,Kh,hd]."""
    B, S, _ = x_normed.shape
    q, k, v = x_normed @ lp["wq"], x_normed @ lp["wk"], x_normed @ lp["wv"]
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, S, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def mlp_block(
    lp: Params, x: jax.Array, cfg: LlamaConfig, valid: jax.Array | None = None
) -> jax.Array:
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    # jax.nn.gelu's default tanh approximation IS HF's gelu_pytorch_tanh
    act = jax.nn.silu if cfg.mlp_act == "silu" else jax.nn.gelu
    if cfg.num_experts > 0:
        return _moe_mlp(lp, h, cfg, act, valid).astype(x.dtype)
    gate = act((h @ lp["w_gate"]).astype(jnp.float32)).astype(x.dtype)
    return ((gate * (h @ lp["w_up"])) @ lp["w_down"]).astype(x.dtype)


def _moe_mlp(
    lp: Params, h: jax.Array, cfg: LlamaConfig, act, valid: jax.Array | None = None
) -> jax.Array:
    """Mixtral-style top-k MoE FFN, two formulations (cfg.moe_impl):

    - "dense" (default): every expert computes, a top-k-masked softmax
      weights the outputs. Static shapes, exact top-k semantics. On DECODE
      this costs the same HBM as sparse dispatch — ALL expert weights stream
      from HBM per step regardless — and decode is weight-bound, so the
      extra FLOPs are largely free at serving batch sizes.
    - "sparse": capacity-based scatter dispatch (models/moe.py). PREFILL is
      compute-bound and dense-mix pays E/top_k× the MLP FLOPs there, so the
      engine flips its prefill cfg to sparse via
      EngineConfig.moe_prefill_impl; over-capacity tokens lose that
      expert's contribution (cfg.moe_capacity_factor sizes the headroom)."""
    from agentfield_tpu.models.moe import topk_router_weights
    from agentfield_tpu.models.quant import QuantW

    def emm(spec, x, w):  # expert contraction, int8-aware
        return w.expert_einsum(spec, x) if isinstance(w, QuantW) else jnp.einsum(spec, x, w)

    if cfg.moe_impl == "sparse":
        return _moe_mlp_sparse(lp, h, cfg, act, emm, valid)
    if cfg.moe_impl != "dense":
        raise ValueError(f"moe_impl={cfg.moe_impl!r} must be 'dense' or 'sparse'")
    logits = (h @ lp["router"]).astype(jnp.float32)  # [B, S, E]
    weights = topk_router_weights(logits, cfg.num_experts_per_tok)
    gate = act(emm("bsd,edf->besf", h, lp["w_gate"]).astype(jnp.float32)).astype(h.dtype)
    up = emm("bsd,edf->besf", h, lp["w_up"])
    y = emm("besf,efd->besd", gate * up, lp["w_down"])
    return jnp.einsum("bse,besd->bsd", weights.astype(y.dtype), y)


def _moe_mlp_sparse(
    lp: Params, h: jax.Array, cfg: LlamaConfig, act, emm,
    valid: jax.Array | None = None,  # [B, S] bool: serving prefills exclude
    # bucket padding so it cannot consume expert capacity ahead of real
    # tokens (dense-mix needs no mask — padding rows are discarded downstream)
) -> jax.Array:
    """Capacity-based sparse dispatch for the gated (gate/up/down) MoE FFN:
    scatter the routed tokens into [E, capacity, D] buffers, run each
    expert's FFN on its buffer only, gather + weight-sum back. FFN FLOPs
    ∝ top_k * capacity_factor instead of num_experts."""
    from agentfield_tpu.models.moe import (
        combine_tokens,
        dispatch_tokens,
        expert_capacity,
        sparse_plan,
    )

    b, s, d = h.shape
    n = b * s
    k = cfg.num_experts_per_tok
    capacity = expert_capacity(n, cfg.num_experts, k, cfg.moe_capacity_factor)
    xt = h.reshape(n, d)
    logits = (xt @ lp["router"]).astype(jnp.float32)  # [N, E]
    experts, slots, keep, weights = sparse_plan(
        logits, k, capacity, None if valid is None else valid.reshape(n)
    )
    buf = dispatch_tokens(xt, experts, slots, cfg.num_experts, capacity)
    gate = act(emm("ecd,edf->ecf", buf, lp["w_gate"]).astype(jnp.float32)).astype(h.dtype)
    up = emm("ecd,edf->ecf", buf, lp["w_up"])
    y = emm("ecf,efd->ecd", gate * up, lp["w_down"])
    out = combine_tokens(y, experts, slots, keep, weights, k)
    return out.reshape(b, s, d).astype(h.dtype)


def unembed(params: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    h = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    w = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return (h @ w).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Full forward (no cache / contiguous cache)
# ---------------------------------------------------------------------------


def forward_impl(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,
    positions: jax.Array,
    collect_kv: bool = True,
    remat: bool = False,
    attn_impl: str = "ref",
    mesh=None,  # required (static) for attn_impl="ring"
    embeds_override: tuple[jax.Array, jax.Array] | None = None,
    valid_mask: jax.Array | None = None,  # [B, S] bool: which tokens are
    # real (serving prefills mark bucket padding False so sparse-MoE
    # dispatch cannot let padding consume expert capacity; the dense paths
    # ignore it — padded outputs are discarded downstream either way)
    return_hidden: bool = False,  # return final-norm hidden states [B,S,D]
    # instead of logits (embeddings path — skips the unembed matmul)
):
    """Dense causal forward. tokens/positions: [B, S].

    ``embeds_override=(inject [B, S, D], mask [B, S] bool)`` substitutes
    non-token embeddings at masked positions (multimodal early fusion: the
    vision tower's patch embeddings replace placeholder tokens —
    models/vision.py; reference analogue: image parts forwarded to external
    providers, agent_ai.py:449-520).

    Returns (logits [B, S, V] float32, (k, v) each [L, B, S, Kh, hd]) — the
    per-layer K/V are the scan outputs, free to collect, and are what a
    serving prefill writes into the paged cache. Training passes
    ``collect_kv=False`` (don't materialize caches) and ``remat=True``
    (rematerialize the layer body in backward, trading FLOPs for HBM).
    """
    x = embed_tokens(params, cfg, tokens)
    if embeds_override is not None:
        inject, inj_mask = embeds_override
        x = jnp.where(inj_mask[..., None], inject.astype(x.dtype), x)
    cos, sin = rope_sincos(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    # A window that can't bind within this sequence length is a no-op —
    # kernels stay usable for short-context serving of windowed models.
    win = cfg.sliding_window
    if win is not None and win >= tokens.shape[1]:
        win = None

    def attend(q, k, v):
        if attn_impl == "flash":
            # Dense causal prefill through the ONE ragged paged-attention
            # kernel (the standalone flash kernel is deleted — docs/
            # KERNELS.md): each batch row packs as same-seq ragged rows over
            # an empty pool, so the whole forward runs in the kernel's
            # same-launch new-key phase (the flash recurrence, with causal
            # slice skipping). Valid whenever positions are per-row aranges
            # (prefill), which is what the serving engine guarantees.
            # Interpreted on CPU backends. With a TP mesh it runs under
            # shard_map over the head axis (each shard: full sequence, H/tp
            # query + Kh/tp KV heads; zero collectives — the wo psum
            # downstream is the only traffic).
            import functools

            from agentfield_tpu.ops.pallas.ragged_paged_attention_kernel import (
                dense_causal_attention,
            )

            fa = functools.partial(
                dense_causal_attention, window=win,
                interpret=jax.default_backend() == "cpu",
            )
            if mesh is not None:
                from jax.sharding import PartitionSpec as P
                from jax.experimental.shard_map import shard_map

                from agentfield_tpu.parallel.mesh import AXIS_MODEL

                if mesh.shape.get(AXIS_MODEL, 1) > 1:
                    spec = P(None, None, AXIS_MODEL, None)
                    fa = shard_map(
                        fa, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_rep=False,
                    )
            return fa(q, k, v)
        if attn_impl == "ring":
            # Sequence/context parallelism: S shards over the mesh's `seq`
            # axis — long-context training where no device holds the full
            # sequence. Positions travel the ring with K/V, so offset/
            # continuation layouts mask exactly like attention_ref (they must
            # be strictly increasing along the sequence).
            from agentfield_tpu.parallel.mesh import AXIS_SEQ
            from agentfield_tpu.parallel.ring_attention import ring_attention

            if mesh is None or AXIS_SEQ not in getattr(mesh, "shape", {}):
                raise ValueError(
                    "attn_impl='ring' requires mesh= with a 'seq' axis "
                    f"(got {mesh!r})"
                )
            return ring_attention(
                q, k, v, mesh, causal=True, positions=positions, window=win
            )
        return attention_ref(
            q, k, v, positions, positions, jnp.ones_like(positions, bool),
            window=win,
        )

    def body(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = qkv_proj(lp, h, cfg, cos, sin)
        attn = attend(q, k, v)
        x = x + (attn.reshape(*attn.shape[:2], -1) @ lp["wo"]).astype(x.dtype)
        x = x + mlp_block(lp, x, cfg, valid_mask)
        return x, ((k, v) if collect_kv else None)

    if remat:
        body = jax.checkpoint(body)
    x, kv = jax.lax.scan(body, x, params["layers"])
    if return_hidden:
        h = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        return h, kv
    return unembed(params, cfg, x), kv


forward = jax.jit(
    forward_impl,
    static_argnames=("cfg", "collect_kv", "remat", "attn_impl", "mesh", "return_hidden"),
)


def make_contiguous_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype: str | None = None):
    dt = resolve_dtype(dtype or cfg.dtype)
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def forward_with_cache(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S]
    cache: dict[str, jax.Array],
    offset: jax.Array,  # scalar int32: write position (rows aligned; ragged
    # batches are the paged engine's job, serving/engine.py)
):
    """Incremental forward over a contiguous KV cache (simple generation path,
    used for correctness testing of the paged engine and by __graft_entry__).
    """
    B, S = tokens.shape
    T = cache["k"].shape[2]
    positions = offset + jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, 0)
    x = embed_tokens(params, cfg, tokens)
    cos, sin = rope_sincos(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    k_pos = jnp.arange(T, dtype=jnp.int32)[None, :].repeat(B, 0)
    k_valid = k_pos < (offset + S)
    win = cfg.sliding_window
    if win is not None and win >= T:
        win = None  # can't bind within this cache budget

    def body(x, xs):
        lp, ck, cv = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = qkv_proj(lp, h, cfg, cos, sin)
        ck = jax.lax.dynamic_update_slice(ck, k, (0, offset, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v, (0, offset, 0, 0))
        attn = attention_ref(q, ck, cv, positions, k_pos, k_valid, window=win)
        x = x + (attn.reshape(B, S, -1) @ lp["wo"]).astype(x.dtype)
        x = x + mlp_block(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    return unembed(params, cfg, x), {"k": ks, "v": vs}


def generate_greedy(params, cfg: LlamaConfig, prompt: jax.Array, num_steps: int, max_len: int):
    """Greedy decode via the contiguous cache — a correctness oracle for the
    continuous-batching engine, not the serving path."""
    B, S = prompt.shape
    if num_steps < 1:
        raise ValueError(f"num_steps must be >= 1, got {num_steps}")
    # The final generated token is returned but never written to the cache,
    # so only S + num_steps - 1 slots are needed.
    if S + num_steps - 1 > max_len:
        raise ValueError(
            f"prompt ({S}) + num_steps ({num_steps}) - 1 exceeds max_len ({max_len}); "
            "dynamic_update_slice would silently clamp the cache write"
        )
    cache = make_contiguous_cache(cfg, B, max_len)
    logits, cache = forward_with_cache(params, cfg, prompt, cache, jnp.int32(0))
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    out = [tok]
    for i in range(num_steps - 1):
        logits, cache = forward_with_cache(params, cfg, tok[:, None], cache, jnp.int32(S + i))
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(tok)
    return jnp.stack(out, axis=1)
