from agentfield_tpu.models.configs import (  # noqa: F401
    LlamaConfig,
    PRESETS,
    get_config,
)
from agentfield_tpu.models.llama import (  # noqa: F401
    init_params,
    forward,
    make_contiguous_cache,
)
