"""Model configuration presets for the Llama family.

The reference delegates all model choice to external providers via litellm
(reference: sdk/python/agentfield/agent_ai.py:342-343, model fallback chain at
agent_ai.py:345-384); here models are in-tree, so configs are first-class.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1/3.2-style RoPE frequency rescaling (HF ``rope_scaling`` with
    ``rope_type="llama3"``). Wavelengths past ``original_max_position_embeddings
    / low_freq_factor`` are divided by ``factor``; a smooth ramp interpolates
    between the high- and low-frequency cutoffs."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500_000.0
    rope_scaling: RopeScaling | None = None  # llama3-style frequency rescaling
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    attn_bias: bool = False  # Qwen2-style QKV projection biases
    mlp_act: str = "silu"  # gate activation: "silu" (llama) | "gelu"
    # (gemma's gelu_pytorch_tanh)
    norm_offset: bool = False  # gemma RMSNorm computes x*(1+w). Convention:
    # params store RUNTIME weights (hf_loader adds the 1.0 at load), so the
    # forward stays one code path
    scale_embeddings: bool = False  # gemma multiplies token embeddings by
    # sqrt(hidden_size) after lookup (unembed uses the RAW tied table)
    sliding_window: int | None = None  # Mistral/Qwen2/Phi-3-style windowed
    # attention: each query attends the most recent `sliding_window` keys
    # only. Served EVERYWHERE: ref paths, the pallas kernels (flash / paged
    # decode / paged chunk — window applied in-kernel with block/page
    # skipping, so a bound window reads O(window) K/V), and ring attention
    # (whole-block skips over the traveling positions)
    num_experts: int = 0  # >0 → Mixtral-style MoE FFN: per-layer router
    # [d, E] + expert-stacked gate/up/down [E, ...]; top-k routing with
    # softmax over the selected experts' logits
    num_experts_per_tok: int = 2
    moe_impl: str = "dense"  # MoE FFN formulation: "dense" soft-routes every
    # expert (exact — the oracle); "sparse" runs capacity-based top-k
    # dispatch (FLOPs ∝ top_k; over-capacity tokens lose that expert's
    # contribution). Serving flips this on its PREFILL cfg only
    # (EngineConfig.moe_prefill_impl) — prefill is compute-bound, decode is
    # weight-bound so dense-mix costs the same HBM there.
    moe_capacity_factor: float = 2.0  # sparse dispatch headroom: per-expert
    # capacity = ceil(tokens * top_k / num_experts * factor)
    # dtype name, resolved lazily so configs stay hashable / serializable
    dtype: str = "bfloat16"

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def num_params(self) -> int:
        """Approximate parameter count (for memory planning)."""
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        if self.num_experts > 0:
            mlp = d * self.num_experts + 3 * d * f * self.num_experts
        else:
            mlp = 3 * d * f
        per_layer = d * (self.q_dim + 2 * self.kv_dim) + self.q_dim * d + mlp + 2 * d
        if self.attn_bias:
            per_layer += self.q_dim + 2 * self.kv_dim
        embed = v * d * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + embed + d


PRESETS: dict[str, LlamaConfig] = {
    # Tiny config for unit tests — MXU-aligned dims, trivially fast on CPU.
    # hermetic speculative-decoding draft: llama-tiny's vocab, quarter the
    # width — pairs with llama-tiny in engine tests (spec_k / draft-verify)
    "llama-nano": LlamaConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=1,
        num_heads=2,
        num_kv_heads=1,
        head_dim=32,
        max_seq_len=256,
    ),
    # draft-scale model sharing the Llama-3 vocabulary: the speculative
    # decoding companion for the 1B/8B targets (random-init until a trained
    # draft checkpoint is pointed at via spec_draft=<dir>)
    "llama-3.2-draft": LlamaConfig(
        vocab_size=128256,
        hidden_size=512,
        intermediate_size=2048,
        num_layers=4,
        num_heads=8,
        num_kv_heads=2,
        head_dim=64,
        tie_embeddings=True,
        max_seq_len=8192,
    ),
    "llama-tiny": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        max_seq_len=256,
        dtype="float32",
    ),
    # llama-3-70b's GQA shape in miniature (8 KV heads, group size 8): the
    # TP=8 serving-validation config — 1 KV head per device, exactly the
    # north-star config-5 carve (BASELINE.md) where KV-page layout bugs live.
    "llama-tiny-tp8": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=64,
        num_kv_heads=8,
        head_dim=16,
        max_seq_len=256,
        dtype="float32",
    ),
    # A mid-size config for single-chip smoke benches (~0.3B).
    "llama-smoke": LlamaConfig(
        vocab_size=32768,
        hidden_size=1024,
        intermediate_size=4096,
        num_layers=8,
        num_heads=16,
        num_kv_heads=8,
        head_dim=64,
        max_seq_len=4096,
    ),
    # Llama 3.2 1B (north-star config 1: greeting-agent smoke model).
    "llama-3.2-1b": LlamaConfig(
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        tie_embeddings=True,
        max_seq_len=8192,
        # HF meta-llama/Llama-3.2-1B config.json rope_scaling (rope_type=llama3)
        rope_scaling=RopeScaling(
            factor=32.0,
            low_freq_factor=1.0,
            high_freq_factor=4.0,
            original_max_position_embeddings=8192,
        ),
    ),
    # Llama 3 8B (primary north-star model).
    "llama-3-8b": LlamaConfig(
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=8192,
    ),
    # Mistral-7B: same decoder family (GQA, rotate-half RoPE, SwiGLU) —
    # served by the identical code path.
    "mistral-7b": LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=10000.0,
        max_seq_len=32768,
        sliding_window=4096,  # Mistral-7B-v0.1 windowed attention
    ),
    # Gemma (v1): GeGLU MLP, RMSNorm x*(1+w), sqrt(d)-scaled embeddings,
    # MQA (2B) / MHA (7B), 256-wide heads, tied embeddings.
    "gemma-2b": LlamaConfig(
        vocab_size=256000,
        hidden_size=2048,
        intermediate_size=16384,
        num_layers=18,
        num_heads=8,
        num_kv_heads=1,
        head_dim=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        max_seq_len=8192,
        tie_embeddings=True,
        mlp_act="gelu",
        norm_offset=True,
        scale_embeddings=True,
    ),
    "gemma-7b": LlamaConfig(
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_layers=28,
        num_heads=16,
        num_kv_heads=16,
        head_dim=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        max_seq_len=8192,
        tie_embeddings=True,
        mlp_act="gelu",
        norm_offset=True,
        scale_embeddings=True,
    ),
    # hermetic gemma-shaped test config (all three gemma behaviors on)
    "gemma-tiny": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=1,
        head_dim=32,
        rms_norm_eps=1e-6,
        max_seq_len=256,
        tie_embeddings=True,
        mlp_act="gelu",
        norm_offset=True,
        scale_embeddings=True,
    ),
    # Mixtral: Llama architecture with a top-2-of-8 MoE FFN per layer.
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1_000_000.0,
        max_seq_len=32768,
        num_experts=8,
        num_experts_per_tok=2,
    ),
    # hermetic MoE test config (4 experts, top-2)
    "mixtral-tiny": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        max_seq_len=256,
        num_experts=4,
        num_experts_per_tok=2,
    ),
    # microsoft/Phi-3-mini-4k-instruct: llama architecture with fused
    # qkv/gate_up projections in the checkpoint (split at load,
    # hf_loader.py), MHA (32 q = 32 kv heads), vocab 32064, and a
    # 2047-token sliding window (its config.json carries it)
    "phi-3-mini": LlamaConfig(
        vocab_size=32064,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=32,
        num_heads=32,
        num_kv_heads=32,
        head_dim=96,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        max_seq_len=4096,
        sliding_window=2047,
    ),
    # Qwen2-7B: adds QKV projection biases (attn_bias).
    "qwen2-7b": LlamaConfig(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1_000_000.0,
        rms_norm_eps=1e-6,
        max_seq_len=32768,
        attn_bias=True,
    ),
    # Llama 3 70B (TP=8 over ICI, north-star config 5).
    "llama-3-70b": LlamaConfig(
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=8192,
    ),
}


def get_config(name: str) -> LlamaConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown model config {name!r}; known: {sorted(PRESETS)}") from None
