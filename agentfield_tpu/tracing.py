"""Request-scoped distributed tracing + engine flight recorder primitives.

jax-free by design (package root, like ``prefix_hash``/``branching``): the
gateway, the channel layer, and the serving engine all import this module,
and the control plane must be able to assemble traces without dragging the
serving stack onto its event loop.

One execution = ONE trace. The gateway mints a :func:`new_trace_id` per
execution (``Execution.trace_id``), threads a small ``TraceContext`` dict
through the dispatch path — channel ``submit`` frames / the model-node
``generate`` input — and every layer records :class:`spans <Tracer>` against
that id: monotonic-clock begin/end pairs anchored to a wall-clock ``t0`` so
cross-process spans order into one waterfall. Node-side spans accumulate in
a bounded per-process :class:`Tracer` buffer and ride the execution's
terminal frame back to the gateway's :class:`TraceStore`, served at
``GET /api/v1/executions/{id}/trace`` (docs/OBSERVABILITY.md).

Span dict shape (the wire format — plain JSON)::

    {"name": "engine.prefill", "t0": 1722772800.123, "dur_ms": 14.2,
     "attrs": {"tokens": 128, "cached": 96}, "node": "node-a", "attempt": 1}

Always-on siblings (independent of per-request tracing):

- :class:`HistogramSet` — fixed-bucket latency histograms (TTFT / ITL /
  queue-wait / tick-duration) the engine ships on every heartbeat; the
  control plane re-exports them as per-node Prometheus histograms.
- :class:`FlightRecorder` — a fixed-size ring of per-tick scheduler records,
  exposed on the node debug endpoint and dumped when an engine step fails.

Knobs (docs/OBSERVABILITY.md knob table):

- ``AGENTFIELD_TRACE`` — master switch (default on). Off is bit-compatible
  with the pre-tracing wire: no ``trace`` key on any frame or payload.
- ``AGENTFIELD_TRACE_BUFFER_SPANS`` — per-process span buffer cap (node
  side; oldest traces evict whole when the total overflows).
- ``AGENTFIELD_TRACE_TTL_S`` — gateway TraceStore retention after the last
  span of a trace landed.
- ``AGENTFIELD_FLIGHT_TICKS`` — flight-recorder ring size (per-tick rows).
"""

from __future__ import annotations

import bisect
import collections
import os
import threading
import time
import uuid

# Per-trace span cap: one runaway request (a branch fan-out, a preempt storm)
# must not evict every other trace from the buffer.
_MAX_SPANS_PER_TRACE = 512


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


_enabled_override: bool | None = None


def enabled() -> bool:
    """Is request-scoped tracing on? ``AGENTFIELD_TRACE`` (default on),
    overridable in-process via :func:`set_enabled` (tests, the
    ``trace_overhead`` bench A/B). The flight recorder and the latency
    histograms are always-on and do NOT consult this."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("AGENTFIELD_TRACE", "1").lower() not in ("0", "false", "no")


def set_enabled(on: bool | None) -> None:
    """In-process override of the ``AGENTFIELD_TRACE`` knob (None restores
    the env default). The gateway reads :func:`enabled` per execution, so
    flipping this mid-run affects only executions prepared afterwards."""
    global _enabled_override
    _enabled_override = on


def new_trace_id() -> str:
    return f"tr_{uuid.uuid4().hex[:20]}"


def valid_context(ctx) -> dict | None:
    """The one TraceContext validation: a dict with a str ``trace_id`` (plus
    optional ``attempt``/``node`` labels) passes through; anything else —
    client-supplied garbage included — reads as "not traced"."""
    if isinstance(ctx, dict) and isinstance(ctx.get("trace_id"), str):
        return ctx
    return None


def make_span(
    name: str, t0: float, dur_ms: float, attrs: dict | None = None
) -> dict:
    span = {"name": name, "t0": round(t0, 6), "dur_ms": round(dur_ms, 3)}
    if attrs:
        span["attrs"] = attrs
    return span


class Tracer:
    """Bounded per-process span buffer, indexed by trace id.

    Writers are the engine's scheduler thread and the node's event loop;
    readers pop a whole trace at terminal time — one lock serializes both.
    When the total span count overflows ``max_spans`` the OLDEST trace
    evicts whole (a trace with half its spans missing reads as corrupt, not
    as cheap)."""

    def __init__(self, max_spans: int | None = None):
        self.max_spans = max_spans or _env_int("AGENTFIELD_TRACE_BUFFER_SPANS", 8192)
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, list[dict]]" = collections.OrderedDict()
        self._total = 0
        self.dropped_spans = 0  # overflow accounting (debug endpoint)

    def record_span(
        self,
        name: str,
        trace_id: str | None,
        t0: float,
        dur_ms: float,
        attrs: dict | None = None,
    ) -> None:
        """Record one finished span against ``trace_id`` (no-op when None —
        call sites stay unconditional and cheap for untraced requests)."""
        if not trace_id:
            return
        span = make_span(name, t0, dur_ms, attrs)
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
            if len(spans) >= _MAX_SPANS_PER_TRACE:
                self.dropped_spans += 1
                return
            spans.append(span)
            self._total += 1
            while self._total > self.max_spans and len(self._traces) > 1:
                _, evicted = self._traces.popitem(last=False)
                self._total -= len(evicted)
                self.dropped_spans += len(evicted)

    def pop(self, trace_id: str) -> list[dict]:
        """Remove and return a trace's spans (terminal-frame shipping)."""
        with self._lock:
            spans = self._traces.pop(trace_id, None)
            if spans is None:
                return []
            self._total -= len(spans)
            return spans

    def peek(self, trace_id: str) -> list[dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def span_count(self) -> int:
        with self._lock:
            return self._total


_TRACER: Tracer | None = None


def tracer() -> Tracer:
    """The process-wide span buffer (engine + model backend share it; a
    process serves one node, so one buffer is the natural scope)."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer()
    return _TRACER


class TraceStore:
    """Gateway-side trace assembly: spans from every layer and every node
    accumulate under their trace id; ``get`` returns the ordered waterfall.
    In-memory with TTL retention — traces are a debugging substrate, not an
    audit log (the execution row is the durable record; it carries the
    trace id so operators know which trace WOULD have answered)."""

    def __init__(self, retain_s: float | None = None, max_traces: int = 4096):
        self.retain_s = (
            retain_s
            if retain_s is not None
            else float(_env_int("AGENTFIELD_TRACE_TTL_S", 600))
        )
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, tuple[float, list[dict]]]" = (
            collections.OrderedDict()
        )

    def _purge_locked(self) -> None:
        cutoff = time.monotonic() - self.retain_s
        while self._traces:
            tid, (touched, _) = next(iter(self._traces.items()))
            if touched > cutoff and len(self._traces) <= self.max_traces:
                break
            self._traces.pop(tid, None)

    def record_span(
        self,
        name: str,
        trace_id: str | None,
        t0: float,
        dur_ms: float,
        attrs: dict | None = None,
        node: str = "gateway",
    ) -> None:
        """Gateway-local span (dispatch attempts, queue wait, the root):
        recorded straight into the store — the gateway IS the assembly
        point, so it skips the per-process buffer + terminal-frame hop."""
        if not trace_id:
            return
        span = make_span(name, t0, dur_ms, attrs)
        span.setdefault("node", node)
        self.extend(trace_id, [span])

    def extend(self, trace_id: str, spans) -> int:
        """Land shipped spans (terminal frames / result payloads). Shapes
        are validated span-by-span — a malformed payload from one node must
        not poison the trace or the endpoint."""
        if not isinstance(trace_id, str) or not isinstance(spans, list):
            return 0
        ok = [
            s
            for s in spans
            if isinstance(s, dict)
            and isinstance(s.get("name"), str)
            and isinstance(s.get("t0"), (int, float))
            and isinstance(s.get("dur_ms"), (int, float))
        ]
        if not ok:
            return 0
        with self._lock:
            _, existing = self._traces.pop(trace_id, (0.0, []))
            existing.extend(ok[: max(0, _MAX_SPANS_PER_TRACE - len(existing))])
            self._traces[trace_id] = (time.monotonic(), existing)
            self._purge_locked()
        return len(ok)

    def get(self, trace_id: str) -> list[dict]:
        """The assembled waterfall: spans ordered by wall-clock start, with
        the longer span first on ties (a parent that began the same instant
        as its child renders above it)."""
        with self._lock:
            entry = self._traces.get(trace_id)
            spans = list(entry[1]) if entry is not None else []
        return sorted(spans, key=lambda s: (s.get("t0", 0.0), -s.get("dur_ms", 0.0)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


# ---------------------------------------------------------------------------
# Latency histograms (always-on; ride the stats→heartbeat→/metrics pipeline)

# ms-scale buckets for serving latencies: sub-ms ticks through 30s tails.
MS_BUCKETS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class HistogramSet:
    """A fixed family of fixed-bucket latency histograms, cheap enough for
    the scheduler tick path (one bisect + two adds per observe, one shared
    lock). ``snapshot()`` is the heartbeat payload — cumulative counters,
    so the control plane re-publishes the latest snapshot per node exactly
    like the engine's counter gauges (the node owns the counter)."""

    def __init__(self, names: tuple[str, ...], buckets: tuple[float, ...] = MS_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        # per name: per-bucket counts (+1 overflow slot), sum, count
        self._h: dict[str, list] = {
            n: [[0] * (len(self.buckets) + 1), 0.0, 0] for n in names
        }

    def observe(self, name: str, value_ms: float) -> None:
        h = self._h.get(name)
        if h is None:
            raise KeyError(f"histogram {name!r} is not in this set")
        i = bisect.bisect_left(self.buckets, value_ms)
        with self._lock:
            h[0][i] += 1
            h[1] += value_ms
            h[2] += 1

    def snapshot(self) -> dict:
        """{name: {buckets, counts (per-bucket, +Inf last), sum, count}} —
        JSON-safe, shipped verbatim in heartbeat stats under
        ``latency_hist`` (popped by the registry like ``prefix_sketch``)."""
        with self._lock:
            return {
                name: {
                    "buckets": list(self.buckets),
                    "counts": list(h[0]),
                    "sum": round(h[1], 3),
                    "count": h[2],
                }
                for name, h in self._h.items()
            }


# ---------------------------------------------------------------------------
# Flight recorder (always-on ring of per-tick scheduler records)


class FlightRecorder:
    """Fixed-size ring of per-tick engine records — the crash-dump substrate
    for "why was this tick slow / what was the engine doing when it died".
    Appends are deque-atomic (scheduler thread); snapshots copy (event
    loop). Dumped on engine-step failure and served by the node debug
    endpoint ``GET /debug/flight`` (docs/OBSERVABILITY.md)."""

    def __init__(self, max_ticks: int | None = None):
        self.max_ticks = max_ticks or _env_int("AGENTFIELD_FLIGHT_TICKS", 512)
        self._ring: collections.deque[dict] = collections.deque(maxlen=self.max_ticks)
        self.ticks_recorded = 0

    def record(self, row: dict) -> None:
        self._ring.append(row)
        self.ticks_recorded += 1

    def snapshot(self, last: int | None = None) -> list[dict]:
        rows = list(self._ring)
        return rows[-last:] if last else rows
