"""Quantized KV-page representation: int8 / fp8 pages + per-slot scales.

One definition of the quantized page format every layer shares (kernel,
XLA reference, pool scatter, host tier, wire — docs/KERNELS.md "Quantized
pages"): K/V values are stored in the quantized dtype with ONE f32 scale
per (token slot, kv head) — the scale is the max-abs of that token's head
vector over ``head_dim`` divided by the dtype's representable max. Per-slot
(not per-page) scales are what make the fused in-kernel write exact and
cheap: patching a token into a partially filled page touches only that
slot's value row and scale — no dequant/requant of neighbouring slots, no
garbage-slot content inflating a shared scale, and a demote→restore or
cross-node round trip of the raw bytes is bit-exact by construction.

``QuantPages`` is a pytree, so a quantized pool flows through every jitted
scheduler path (scan carries, donation, device_put/sharding) exactly like
the plain bf16 array it replaces — host code that only moves pools around
never branches on the representation.

Quantization math (shared verbatim by the Pallas kernel's write phase and
``kv_quantize`` so the fused write and the XLA scatter stay BIT-exact):

    scale = max(max_abs(vals over head_dim) / QMAX, 1e-20)
    int8:  q = clip(round(vals / scale), -127, 127)
    fp8:   q = (vals / scale).astype(float8_e4m3fn)   # RTNE cast

Dequantization is ``q.astype(f32) * scale`` everywhere. Storage cost per
(page, kv-head): ``ps * hd`` bytes of values + ``4 * ps`` bytes of scale —
vs ``2 * ps * hd`` for bf16, i.e. ~1.9x pages per HBM byte at hd=64+.
"""

from __future__ import annotations

import typing

import jax.numpy as jnp

KV_QUANT_DTYPES = ("none", "int8", "fp8")

# fp8 storage uses e4m3 (max normal 448): KV values are small-magnitude and
# per-slot scales normalize into the format's sweet spot; e5m2's extra
# exponent range buys nothing here and costs a mantissa bit.
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)

QMAX = {"int8": 127.0, "fp8": 448.0}
# Scales multiply by the PRECOMPUTED reciprocal instead of dividing by
# QMAX: XLA rewrites division-by-constant into a reciprocal multiply under
# jit but not in eager mode (1-ulp divergence), and the parity battery
# compares the eager XLA reference against the jitted kernel bit-for-bit —
# a single constant multiply is the same instruction in both.
INV_QMAX = {m: 1.0 / v for m, v in QMAX.items()}

SCALE_FLOOR = 1e-20  # all-zero vectors quantize to 0 with a harmless scale


class QuantPages(typing.NamedTuple):
    """A quantized page pool: values + per-slot scales, as ONE pytree.

    - ``q``     — ``[..., P, Kh, ps, hd]`` int8 / float8_e4m3fn values
    - ``scale`` — ``[..., P, Kh, ps]`` float32 per-(slot, kv-head) scales

    The leading dims match (the engine stacks layers on axis 0; a
    ``lax.scan`` over layers slices both leaves together).
    """

    q: typing.Any
    scale: typing.Any

    @property
    def dtype(self):  # convenience: the VALUE dtype names the mode
        return self.q.dtype

    @property
    def shape(self):
        return self.q.shape


def quant_mode_supported(mode: str) -> bool:
    return mode in ("none", "int8") or (mode == "fp8" and _FP8_DTYPE is not None)


def quant_value_dtype(mode: str):
    """jnp dtype storing quantized values for ``mode`` (raises on 'none')."""
    if mode == "int8":
        return jnp.int8
    if mode == "fp8":
        if _FP8_DTYPE is None:
            raise ValueError(
                "kv_quant_dtype='fp8' needs jax.numpy.float8_e4m3fn, which "
                "this jax build does not provide — use 'int8' or 'none'"
            )
        return _FP8_DTYPE
    raise ValueError(f"no quantized value dtype for mode {mode!r}")


def quant_mode_of(pages) -> str:
    """The kv-quant mode a pool operand encodes ('none' for plain arrays)."""
    if not isinstance(pages, QuantPages):
        return "none"
    if pages.q.dtype == jnp.int8:
        return "int8"
    return "fp8"


def kv_quantize(vals, mode: str):
    """Per-slot quantization of ``vals [..., hd]`` → ``(q [..., hd],
    scale [...])``. The ONE quantization formula (module docstring): the
    Pallas kernel's write phase inlines exactly this math, which is what
    keeps fused-kernel and XLA-reference pool writes bit-identical."""
    f = vals.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(f), axis=-1) * INV_QMAX[mode], SCALE_FLOOR
    )
    y = f / scale[..., None]
    if mode == "int8":
        q = jnp.clip(jnp.round(y), -127.0, 127.0).astype(jnp.int8)
    else:
        q = y.astype(quant_value_dtype(mode))
    return q, scale


def kv_dequantize(q, scale):
    """``q [..., hd]`` + ``scale [...]`` → float32 values."""
    return q.astype(jnp.float32) * scale[..., None]


def write_pages(pages, vals, page_ids, slot_ids):
    """Scatter per-token K or V vectors into a (possibly quantized) page
    pool — the ONE write expression the engine's XLA prefill scatters use.

    ``pages`` is ``[L, P, Kh, ps, hd]`` (plain) or the matching
    :class:`QuantPages`; ``vals`` is ``idx_shape + [L, Kh, hd]`` (the
    advanced-index value layout of ``pages.at[:, page_ids, :, slot_ids]``
    with ``page_ids``/``slot_ids`` of shape ``idx_shape``)."""
    if isinstance(pages, QuantPages):
        q, s = kv_quantize(vals, quant_mode_of(pages))
        return QuantPages(
            pages.q.at[:, page_ids, :, slot_ids].set(q),
            pages.scale.at[:, page_ids, :, slot_ids].set(s),
        )
    return pages.at[:, page_ids, :, slot_ids].set(vals.astype(pages.dtype))
