from agentfield_tpu.ops.paged_attention import (  # noqa: F401
    RaggedRows,
    paged_attention,  # deprecated shim — ragged_paged_attention replaces it
    paged_attention_ref,
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
