from agentfield_tpu.ops.paged_attention import paged_attention  # noqa: F401
