"""Pallas TPU paged KV-cache write (decode hot path).

Each decode step appends one token's K/V per sequence into its current
page: a [B]-row scatter at (page_idx[b], :, slot_idx[b], :). XLA lowers
that advanced-index scatter poorly on TPU (row-serialized scatter loop);
this kernel instead walks the batch on the grid, DMAs each sequence's
single page to VMEM, patches one slot, and writes it back — with
input/output aliasing so the pool is updated in place.

Page-collision note: live sequences own their pages exclusively, so grid
steps touch disjoint pages — except the garbage page 0 shared by inactive
rows, whose content is meaningless by contract (any write order is fine).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kv_write_kernel(
    page_idx_ref,  # [B] int32 (scalar prefetch)
    slot_idx_ref,  # [B] int32 (scalar prefetch)
    kp_ref,  # [1, Kh, ps, hd] — the page this row writes into
    vp_ref,  # [1, Kh, ps, hd]
    kn_ref,  # [1, Kh, hd]
    vn_ref,  # [1, Kh, hd]
    kp_out,  # [1, Kh, ps, hd] (aliased with the pool)
    vp_out,  # [1, Kh, ps, hd]
):
    b = pl.program_id(0)
    slot = slot_idx_ref[b]
    # Carry the page through (out VMEM blocks start uninitialized), then
    # patch the one slot this token occupies.
    kp_out[...] = kp_ref[...]
    vp_out[...] = vp_ref[...]
    kp_out[0, :, pl.dslice(slot, 1), :] = kn_ref[0][:, None, :]
    vp_out[0, :, pl.dslice(slot, 1), :] = vn_ref[0][:, None, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def kv_write_pallas(
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,
    k_new: jax.Array,  # [B, Kh, hd]
    v_new: jax.Array,
    page_idx: jax.Array,  # [B] int32 (page 0 = garbage for inactive rows)
    slot_idx: jax.Array,  # [B] int32
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    P, Kh, ps, hd = k_pages.shape
    B = k_new.shape[0]
    spec_page = pl.BlockSpec(
        (1, Kh, ps, hd), lambda b, pi, si: (pi[b], 0, 0, 0), memory_space=pltpu.VMEM
    )
    spec_new = pl.BlockSpec(
        (1, Kh, hd), lambda b, pi, si: (b, 0, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[spec_page, spec_page, spec_new, spec_new],
        out_specs=[spec_page, spec_page],
    )
    return pl.pallas_call(
        _kv_write_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # operand numbering includes the two scalar-prefetch args
        input_output_aliases={2: 0, 3: 1},
        cost_estimate=pl.CostEstimate(
            flops=0,
            bytes_accessed=4 * B * Kh * ps * hd * k_pages.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(page_idx, slot_idx, k_pages, v_pages, k_new, v_new)


def kv_write(k_pages, v_pages, k_new, v_new, page_idx, slot_idx, impl="ref", mesh=None):
    """Dispatch the decode-step KV append. impl='ref' is the XLA scatter;
    'pallas' is the per-page patch kernel. With a TP mesh the kernel runs
    under shard_map over the KV-head axis — the pool and the new K/V shard
    identically, so each shard patches its own heads with no collectives."""
    if impl == "ref":
        k_pages = k_pages.at[page_idx, :, slot_idx].set(k_new)
        v_pages = v_pages.at[page_idx, :, slot_idx].set(v_new)
        return k_pages, v_pages
    if impl != "pallas":
        raise ValueError(f"unknown kv_write impl {impl!r}")
    interpret = jax.default_backend() == "cpu"
    fn = functools.partial(kv_write_pallas, interpret=interpret)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        from agentfield_tpu.parallel.mesh import AXIS_MODEL

        if mesh.shape.get(AXIS_MODEL, 1) > 1:
            fn = shard_map(
                fn,
                mesh=mesh,
                in_specs=(
                    P(None, AXIS_MODEL, None, None),
                    P(None, AXIS_MODEL, None, None),
                    P(None, AXIS_MODEL, None),
                    P(None, AXIS_MODEL, None),
                    P(None),
                    P(None),
                ),
                out_specs=(
                    P(None, AXIS_MODEL, None, None),
                    P(None, AXIS_MODEL, None, None),
                ),
                check_rep=False,
            )
    return fn(k_pages, v_pages, k_new, v_new, page_idx, slot_idx)
