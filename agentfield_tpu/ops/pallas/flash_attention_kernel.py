"""Pallas TPU flash attention (prefill path).

Blockwise-softmax attention that never materializes the [S, T] score matrix:
K/V stream HBM→VMEM through the grid's innermost dimension while running
max/sum statistics rescale a VMEM accumulator (the standard online-softmax
recurrence). Causal blocks above the diagonal are predicated off with
``pl.when``. GQA is expressed in the BlockSpec index maps — query head h
reads kv head ``h // (H // Kh)`` — so no KV repetition is materialized.

Replaces ``models.llama.attention_ref`` inside jitted prefill on TPU; the
einsum reference remains the CPU/test oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref,  # [1, 1, bq, hd]
    k_ref,  # [1, 1, bk, hd]
    v_ref,  # [1, 1, bk, hd]
    o_ref,  # [1, 1, bq, hd]
    m_scr,  # [bq, 1] f32
    l_scr,  # [bq, 1] f32
    acc_scr,  # [bq, hd] f32
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
    window: int | None,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: blocks strictly above the diagonal contribute nothing; with a
    # sliding window, neither do blocks wholly below every query's window
    # (max key pos in block < min query pos - window + 1).
    run = (not causal) or (ki * block_k <= qi * block_q + (block_q - 1))
    if window is not None:
        run = run & (ki * block_k + block_k - 1 > qi * block_q - window)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [bq, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            keep = k_pos <= q_pos
            if window is not None:  # HF Mistral semantics (attention_ref)
                keep &= k_pos > q_pos - window
            s = jnp.where(keep, s, _NEG_INF)

        m_prev = m_scr[...]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # rescale factor for old stats
        p = jnp.exp(s - m_new)  # [bq, bk]
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (can't happen in causal self-attention, but keep
        # the division safe) fall back to 0 via the l floor.
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0, ...] = (acc_scr[...] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "sm_scale", "block_q", "block_k", "interpret", "window"
    ),
)
def flash_attention(
    q: jax.Array,  # [B, H, S, hd]
    k: jax.Array,  # [B, Kh, T, hd]
    v: jax.Array,  # [B, Kh, T, hd]
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    window: int | None = None,  # sliding window over causal positions; with
    # block-level skipping a bound window reads O(S * window) K/V blocks
    # instead of O(S^2 / 2)
) -> jax.Array:
    """Returns [B, H, S, hd]. S and T must be multiples of the block sizes
    (the serving engine's prefill buckets guarantee this); callers with ragged
    lengths pad and mask downstream."""
    B, H, S, hd = q.shape
    Kh, T = k.shape[1], k.shape[2]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh

    def pick_block(n: int, pref: int) -> int:
        # Largest power-of-two tile ≤ pref that divides n — sequence lengths
        # here are always multiples of 16 (engine prefill buckets), but may
        # not be multiples of 128 when max_context caps a bucket (e.g. 192).
        for b in (pref, 128, 64, 32, 16):
            if b <= pref and n % b == 0:
                return b
        raise ValueError(f"sequence length {n} must be a multiple of 16")

    block_q = pick_block(S, min(block_q, S))
    block_k = pick_block(T, min(block_k, T))
    if sm_scale is None:
        sm_scale = hd**-0.5
    num_k_blocks = T // block_k

    if window is not None and not causal:
        raise ValueError("window requires causal=True (HF Mistral semantics)")
    grid = (B, H, S // block_q, num_k_blocks)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
        window=window,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, qi, ki: (b, h // rep, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, qi, ki: (b, h // rep, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * S * T * hd,
            bytes_accessed=(q.size + k.size + v.size + q.size) * q.dtype.itemsize,
            transcendentals=B * H * S * T,
        ),
        interpret=interpret,
    )(q, k, v)
