"""Public Pallas kernel surface.

Callers import from HERE (``agentfield_tpu.ops.pallas``) instead of
deep-importing kernel module paths:

- ``ragged_paged_attention_pallas`` — the ONE ragged paged-attention kernel
  (fused KV write; quantized int8/fp8 pools dequantize in the page-stream
  phase — ragged_paged_attention_kernel.py, docs/KERNELS.md)
- ``ragged_paged_attention`` / ``ragged_paged_attention_ref`` — dispatcher
  and XLA parity reference (ops/paged_attention.py)
- ``dense_causal_attention`` — dense causal prefill THROUGH the ragged
  kernel (``EngineConfig.prefill_impl="flash"`` resolves here; the
  standalone flash-prefill kernel is deleted — docs/KERNELS.md)
- ``RaggedRows`` — the host-side row-descriptor type
  (built by ``serving.kv_cache.pack_ragged_rows``)
- ``QuantPages`` — the quantized page-pool pytree (ops/kv_quant.py,
  ``EngineConfig.kv_quant_dtype``)
- ``KernelBlocks`` / ``lookup_blocks`` — the autotuned block-size table,
  keyed by KV dtype (kernel_autotune.py, ``AGENTFIELD_KERNEL_AUTOTUNE``)

The four pre-ragged kernel names (decode ``paged_attention_pallas``, chunk
``paged_chunk_attention_pallas``, batched-chunk
``paged_batch_chunk_attention_pallas``/``_ref``, decode-append
``kv_write_pallas``/``kv_write``) were deprecation shims for one release
after the ragged consolidation and are REMOVED; ``flash_attention`` (the
standalone dense prefill kernel) is likewise gone — every shape they
served is a ragged-row mix (docs/KERNELS.md maps the old call forms onto
``ragged_paged_attention``).
"""

from __future__ import annotations

from agentfield_tpu.ops.kv_quant import QuantPages  # noqa: F401
from agentfield_tpu.ops.paged_attention import (  # noqa: F401
    RaggedRows,
    paged_attention_ref,
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from agentfield_tpu.ops.pallas.kernel_autotune import (  # noqa: F401
    KernelBlocks,
    lookup_blocks,
)
from agentfield_tpu.ops.pallas.ragged_paged_attention_kernel import (  # noqa: F401
    dense_causal_attention,
    ragged_paged_attention_pallas,
)

__all__ = [
    "QuantPages",
    "RaggedRows",
    "KernelBlocks",
    "dense_causal_attention",
    "lookup_blocks",
    "paged_attention_ref",
    "ragged_paged_attention",
    "ragged_paged_attention_pallas",
    "ragged_paged_attention_ref",
]
