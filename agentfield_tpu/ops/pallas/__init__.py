"""Public Pallas kernel surface.

Callers import from HERE (``agentfield_tpu.ops.pallas``) instead of
deep-importing kernel module paths:

- ``ragged_paged_attention_pallas`` — the one ragged paged-attention kernel
  (fused KV write; ragged_paged_attention_kernel.py, docs/KERNELS.md)
- ``ragged_paged_attention`` / ``ragged_paged_attention_ref`` — dispatcher
  and XLA parity reference (ops/paged_attention.py)
- ``RaggedRows`` — the host-side row-descriptor type
  (built by ``serving.kv_cache.pack_ragged_rows``)
- ``KernelBlocks`` / ``lookup_blocks`` — the autotuned block-size table
  (kernel_autotune.py, ``AGENTFIELD_KERNEL_AUTOTUNE``)
- ``flash_attention`` — dense prefill flash kernel

The four pre-ragged kernel names (decode ``paged_attention_pallas``, chunk
``paged_chunk_attention_pallas``, batched-chunk
``paged_batch_chunk_attention_pallas``/``_ref``, decode-append
``kv_write_pallas``/``kv_write``) were deprecation shims for one release
after the ragged consolidation and are now REMOVED — every shape they
served is a ragged-row mix (docs/KERNELS.md maps the old call forms onto
``ragged_paged_attention``).
"""

from __future__ import annotations

from agentfield_tpu.ops.paged_attention import (  # noqa: F401
    RaggedRows,
    paged_attention_ref,
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from agentfield_tpu.ops.pallas.flash_attention_kernel import (  # noqa: F401
    flash_attention,
)
from agentfield_tpu.ops.pallas.kernel_autotune import (  # noqa: F401
    KernelBlocks,
    lookup_blocks,
)
from agentfield_tpu.ops.pallas.ragged_paged_attention_kernel import (  # noqa: F401
    ragged_paged_attention_pallas,
)

__all__ = [
    "RaggedRows",
    "KernelBlocks",
    "flash_attention",
    "lookup_blocks",
    "paged_attention_ref",
    "ragged_paged_attention",
    "ragged_paged_attention_pallas",
    "ragged_paged_attention_ref",
]
