"""Public Pallas kernel surface.

Callers import from HERE (``agentfield_tpu.ops.pallas``) instead of
deep-importing kernel module paths:

- ``ragged_paged_attention_pallas`` — the one ragged paged-attention kernel
  (fused KV write; ragged_paged_attention_kernel.py, docs/KERNELS.md)
- ``ragged_paged_attention`` / ``ragged_paged_attention_ref`` — dispatcher
  and XLA parity reference (ops/paged_attention.py)
- ``RaggedRows`` — the host-side row-descriptor type
  (built by ``serving.kv_cache.pack_ragged_rows``)
- ``KernelBlocks`` / ``lookup_blocks`` — the autotuned block-size table
  (kernel_autotune.py, ``AGENTFIELD_KERNEL_AUTOTUNE``)
- ``flash_attention`` — dense prefill flash kernel

The four pre-ragged kernels (decode ``paged_attention_pallas``, chunk
``paged_chunk_attention_pallas``, batched-chunk
``paged_batch_chunk_attention_pallas``/``_ref``, decode-append
``kv_write_pallas``/``kv_write``) are DEPRECATED shims for one release:
same signatures and results, now served by the ragged reference math
(their specialized Mosaic lowerings are gone — new code uses the ragged
kernel, which also covers every one of their shapes).
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp

from agentfield_tpu.ops.paged_attention import (  # noqa: F401
    RaggedRows,
    paged_attention_ref,
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from agentfield_tpu.ops.pallas.flash_attention_kernel import (  # noqa: F401
    flash_attention,
)
from agentfield_tpu.ops.pallas.kernel_autotune import (  # noqa: F401
    KernelBlocks,
    lookup_blocks,
)
from agentfield_tpu.ops.pallas.ragged_paged_attention_kernel import (  # noqa: F401
    ragged_paged_attention_pallas,
)

__all__ = [
    "RaggedRows",
    "KernelBlocks",
    "flash_attention",
    "lookup_blocks",
    "paged_attention_ref",
    "ragged_paged_attention",
    "ragged_paged_attention_pallas",
    "ragged_paged_attention_ref",
    # deprecated shims
    "kv_write",
    "kv_write_pallas",
    "paged_attention_pallas",
    "paged_batch_chunk_attention_pallas",
    "paged_batch_chunk_attention_ref",
    "paged_chunk_attention_pallas",
]


def _warn(old: str) -> None:
    warnings.warn(
        f"agentfield_tpu.ops.pallas.{old} is deprecated; use "
        "ragged_paged_attention (one ragged kernel, fused KV write) — "
        "removed next release",
        DeprecationWarning,
        stacklevel=3,
    )


def _identity_new_kv(k_pages, v_pages, page_tables, pos, valid):
    """Gather the K/V already AT the query positions so the ragged path's
    fused write is a no-op re-write of identical values (the legacy kernels
    attended pools their callers had pre-written)."""
    ps = k_pages.shape[2]
    maxp = page_tables.shape[1]
    lookup = pos // ps
    page_ids = jnp.where(
        (lookup < maxp) & valid,
        jnp.take_along_axis(page_tables, jnp.minimum(lookup, maxp - 1), axis=1),
        0,
    )
    slot_ids = pos % ps
    return k_pages[page_ids, :, slot_ids], v_pages[page_ids, :, slot_ids]


def _legacy_batch_chunk(
    q, k_pages, v_pages, page_tables, starts, k_lens, sm_scale, window
):
    B, W, H, hd = q.shape
    pos = starts[:, None] + jnp.arange(W, dtype=jnp.int32)[None]
    n_tokens = jnp.clip(k_lens - starts, 0, W).astype(jnp.int32)
    valid = jnp.arange(W, dtype=jnp.int32)[None] < n_tokens[:, None]
    k_new, v_new = _identity_new_kv(k_pages, v_pages, page_tables, pos, valid)
    out, _, _ = ragged_paged_attention_ref(
        q, k_new, v_new, k_pages, v_pages, page_tables,
        starts.astype(jnp.int32), n_tokens, starts.astype(jnp.int32),
        jnp.arange(B, dtype=jnp.int32), sm_scale=sm_scale, window=window,
    )
    return out


def paged_attention_pallas(
    q, k_pages, v_pages, page_tables, seq_lens, sm_scale=None,
    interpret=False, window=None,
):
    """DEPRECATED decode-only attention over a pre-written pool."""
    del interpret
    _warn("paged_attention_pallas")
    pos = jnp.maximum(seq_lens.astype(jnp.int32) - 1, 0)
    return _legacy_batch_chunk(
        q[:, None], k_pages, v_pages, page_tables, pos, seq_lens,
        sm_scale, window,
    )[:, 0]


def paged_batch_chunk_attention_ref(
    q, k_pages, v_pages, page_tables, starts, k_lens, sm_scale=None,
    window=None,
):
    """DEPRECATED batched ragged-window attention (pool pre-written)."""
    _warn("paged_batch_chunk_attention_ref")
    return _legacy_batch_chunk(
        q, k_pages, v_pages, page_tables, starts, k_lens, sm_scale, window
    )


def paged_batch_chunk_attention_pallas(
    q, k_pages, v_pages, page_tables, starts, k_lens, sm_scale=None,
    interpret=False, window=None,
):
    """DEPRECATED batched ragged-window attention (pool pre-written)."""
    del interpret
    _warn("paged_batch_chunk_attention_pallas")
    return _legacy_batch_chunk(
        q, k_pages, v_pages, page_tables, starts, k_lens, sm_scale, window
    )


def paged_chunk_attention_pallas(
    q, k_pages, v_pages, page_table_row, start, k_len, sm_scale=None,
    interpret=False, window=None,
):
    """DEPRECATED single-sequence chunk attention (pool pre-written)."""
    del interpret
    _warn("paged_chunk_attention_pallas")
    return _legacy_batch_chunk(
        q[None], k_pages, v_pages, page_table_row[None],
        jnp.asarray(start, jnp.int32)[None], jnp.asarray(k_len, jnp.int32)[None],
        sm_scale, window,
    )[0]


def kv_write(k_pages, v_pages, k_new, v_new, page_idx, slot_idx, impl="ref", mesh=None):
    """DEPRECATED decode-step KV append (the ragged kernel fuses this)."""
    del mesh
    _warn("kv_write")
    if impl not in ("ref", "pallas"):
        raise ValueError(f"unknown kv_write impl {impl!r}")
    k_pages = k_pages.at[page_idx, :, slot_idx].set(k_new)
    v_pages = v_pages.at[page_idx, :, slot_idx].set(v_new)
    return k_pages, v_pages


def kv_write_pallas(k_pages, v_pages, k_new, v_new, page_idx, slot_idx, interpret=False):
    """DEPRECATED per-page patch kernel (single-row writes only — the
    restriction the ragged kernel's idempotent patch phase removed)."""
    del interpret
    _warn("kv_write_pallas")
    k_pages = k_pages.at[page_idx, :, slot_idx].set(k_new)
    v_pages = v_pages.at[page_idx, :, slot_idx].set(v_new)
    return k_pages, v_pages
