"""Pallas TPU paged CHUNK attention (suffix / chunked prefill).

A chunk of C query tokens (one sequence) at absolute positions
[start, start+C) attends over the sequence's paged K/V — including the
chunk's own freshly-written keys — with an exact causal mask on absolute
positions. This replaces the suffix-prefill path's per-layer page gather
(engine `_suffix_prefill_fn` materializes [max_context, Kh, hd] K/V in HBM
for EVERY layer of EVERY chunk — VERDICT weak #7: chunked long-prompt
prefill pays O(chunks × T × L) bandwidth); here pages stream HBM→VMEM once
per (kv-head, page) grid step and the gathered context never exists.

Same online-softmax page walk as the decode kernel
(paged_attention_kernel.py), widened to C query rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _chunk_kernel(
    page_table_ref,  # [maxp] int32 (scalar prefetch)
    start_ref,  # [1] int32 — absolute position of the chunk's first token
    k_len_ref,  # [1] int32 — total valid keys (start + n_new)
    q_ref,  # [1, C, rep, hd]
    k_ref,  # [1, 1, ps, hd] — the (kv-head, page) tile
    v_ref,  # [1, 1, ps, hd]
    o_ref,  # [1, C, rep, hd]
    m_scr,  # [C * rep, 1] f32
    l_scr,  # [C * rep, 1] f32
    acc_scr,  # [C * rep, hd] f32
    *,
    sm_scale: float,
    page_size: int,
    num_page_steps: int,
    rep: int,
    window: int | None,
):
    pi = pl.program_id(1)
    start = start_ref[0]
    k_len = k_len_ref[0]
    C = q_ref.shape[1]

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Page is relevant iff it holds any key with pos < k_len (valid) — keys
    # past every query position mask out below anyway. With a sliding
    # window, pages wholly before even the FIRST query's window skip.
    relevant = pi * page_size < k_len
    if window is not None:
        relevant &= (pi + 1) * page_size - 1 > start - window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32).reshape(C * rep, -1) * sm_scale
        k = k_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [C*rep, ps]
        k_pos = pi * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        q_pos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep
        keep = (k_pos <= q_pos) & (k_pos < k_len)
        if window is not None:  # HF Mistral semantics (attention_ref)
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(pi == num_page_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, ...] = (acc_scr[...] / l).reshape(C, rep, -1).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret", "window"))
def paged_chunk_attention_pallas(
    q: jax.Array,  # [C, H, hd] — one sequence's chunk of query tokens
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,
    page_table_row: jax.Array,  # [maxp] int32
    start: jax.Array,  # scalar int32 — absolute position of q[0]
    k_len: jax.Array,  # scalar int32 — valid keys (= start + n_new)
    sm_scale: float | None = None,
    interpret: bool = False,
    window: int | None = None,  # sliding window (Mistral) on absolute
    # positions: query at q_pos attends keys in (q_pos - window, q_pos]
) -> jax.Array:
    C, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_table_row.shape[0]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    qg = q.reshape(C, Kh, rep, hd).transpose(1, 0, 2, 3)  # [Kh, C, rep, hd]
    kernel = functools.partial(
        _chunk_kernel, sm_scale=sm_scale, page_size=ps, num_page_steps=maxp,
        rep=rep, window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(Kh, maxp),
        in_specs=[
            pl.BlockSpec(
                (1, C, rep, hd), lambda kvh, pi, pt, st, kl: (kvh, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, ps, hd), lambda kvh, pi, pt, st, kl: (pt[pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, ps, hd), lambda kvh, pi, pt, st, kl: (pt[pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, C, rep, hd), lambda kvh, pi, pt, st, kl: (kvh, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((C * rep, 1), jnp.float32),
            pltpu.VMEM((C * rep, 1), jnp.float32),
            pltpu.VMEM((C * rep, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Kh, C, rep, hd), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * C * H * maxp * ps * hd,
            bytes_accessed=2 * maxp * ps * Kh * hd * k_pages.dtype.itemsize,
            transcendentals=C * H * maxp * ps,
        ),
        interpret=interpret,
    )(page_table_row, start[None], k_len[None], qg, k_pages, v_pages)
    return out.transpose(1, 0, 2, 3).reshape(C, H, hd)
