"""Pallas TPU paged decode attention.

One query token per sequence attends over K/V scattered across HBM pages.
The page indirection lives in the BlockSpec index maps via scalar prefetch
(``PrefetchScalarGridSpec``): the grid's innermost dimension walks each
sequence's page list and the index map looks the physical page id up in the
prefetched page table, so the pipeline DMAs exactly the pages each sequence
owns — the gathered [B, max_ctx] K/V of the reference implementation
(ops/paged_attention.py) is never materialized. Online-softmax statistics
accumulate across pages in VMEM scratch (same recurrence as the flash
kernel). This is the ragged-paged-attention kernel pattern (PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _paged_kernel(
    page_tables_ref,  # [B, maxp] int32 (scalar prefetch)
    seq_lens_ref,  # [B] int32 (scalar prefetch)
    q_ref,  # [1, 1, rep, hd]
    k_ref,  # [1, 1, ps, hd]  — the (page, kv-head) tile picked by the index map
    v_ref,  # [1, 1, ps, hd]
    o_ref,  # [1, 1, rep, hd]
    m_scr,  # [rep, 1] f32
    l_scr,  # [rep, 1] f32
    acc_scr,  # [rep, hd] f32
    *,
    sm_scale: float,
    page_size: int,
    num_page_steps: int,
    window: int | None,
):
    b = pl.program_id(0)
    pi = pl.program_id(2)
    seq_len = seq_lens_ref[b]

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Pages wholly past the sequence end contribute nothing (their DMA may
    # fetch the garbage page; the mask below would zero it anyway, but
    # skipping saves the FLOPs). With a sliding window, pages wholly BEFORE
    # the window skip too — windowed decode touches O(window/ps) pages.
    relevant = pi * page_size < seq_len
    if window is not None:
        relevant &= (pi + 1) * page_size > seq_len - window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [rep, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rep, ps]
        k_pos = pi * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = k_pos < seq_len
        if window is not None:  # the query sits at position seq_len - 1
            keep &= k_pos >= seq_len - window
        s = jnp.where(keep, s, _NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(pi == num_page_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0, ...] = (acc_scr[...] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret", "window"))
def paged_attention_pallas(
    q: jax.Array,  # [B, H, hd]
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [B, maxp] int32
    seq_lens: jax.Array,  # [B] int32 (valid tokens incl. current)
    sm_scale: float | None = None,
    interpret: bool = False,
    window: int | None = None,  # sliding window (Mistral): the query at
    # seq_len-1 attends only keys within the most recent `window`
) -> jax.Array:
    B, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    qg = q.reshape(B, Kh, rep, hd)
    grid = (B, Kh, maxp)
    kernel = functools.partial(
        _paged_kernel, sm_scale=sm_scale, page_size=ps, num_page_steps=maxp,
        window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, rep, hd), lambda b, kvh, pi, pt, sl: (b, kvh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, ps, hd),
                lambda b, kvh, pi, pt, sl: (pt[b, pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, ps, hd),
                lambda b, kvh, pi, pt, sl: (pt[b, pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, rep, hd), lambda b, kvh, pi, pt, sl: (b, kvh, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kh, rep, hd), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * maxp * ps * hd,
            bytes_accessed=2 * B * maxp * ps * hd * k_pages.dtype.itemsize,
            transcendentals=B * H * maxp * ps,
        ),
        interpret=interpret,
    )(page_tables, seq_lens, qg, k_pages, v_pages)
    return out.reshape(B, H, hd)
