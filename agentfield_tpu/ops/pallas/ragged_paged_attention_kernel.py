"""Pallas TPU RAGGED paged attention with a fused KV-cache write.

ONE grid program serves every forward shape the engine issues: R ragged
rows of up to W query tokens, each row at its own absolute start over its
own page table — decode rows (n_tokens=1), prefill chunks (a chunk wider
than W splits into several rows sharing a ``seq_id``), and the speculative
verify window are all just descriptors (see ``ops/paged_attention.py``).
The new K/V ride in as operands and the kernel:

1. walks the row's CACHED pool pages (positions ``< ctx_lens[r]``) with the
   usual online-softmax page stream — pages DMA HBM→VMEM, the gathered
   context never materializes;
2. attends the launch's own new keys (``k_new``) in ``block_n``-token
   slices, masked to the same sequence and causal on absolute positions —
   same-launch keys are NEVER read back from the pool, so the attention
   pass has no read-after-write ordering on the page arrays;
3. patches the new K/V into their pool pages in place
   (``input_output_aliases``). Each write step rebuilds a page as
   copy-then-patch-ALL-launch-tokens targeting it, which makes overlapping
   writes IDEMPOTENT: two rows straddling one page (or a torn read of a
   concurrently written page) both produce the identical final content, so
   the multi-row-write restriction of the old per-page patch kernel is
   unrepresentable here.

Grid is ``(R, kv_heads, maxp + new_steps + write_steps)``; block sizes come
from ``kernel_autotune`` (``AGENTFIELD_KERNEL_AUTOTUNE``). Padding rows
(``n_tokens == 0``) produce zero output and only ever touch the reserved
garbage page 0, whose content is meaningless by contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _ragged_kernel(
    # scalar prefetch
    pt_ref,  # [R, maxp] int32
    starts_ref,  # [R] int32
    ctx_ref,  # [R] int32
    ntok_ref,  # [R] int32
    seq_ref,  # [R] int32
    # inputs
    q_ref,  # [1, 1, W, rep, hd] — the (row, kv-head) tile
    starts2_ref,  # [rn, 1] int32 — this new-step's row slice
    ntok2_ref,  # [rn, 1] int32
    seq2_ref,  # [rn, 1] int32
    tokp_ref,  # [R, W] int32 — per-token target page (-1 = no write)
    toks_ref,  # [R, W] int32 — per-token target slot
    kn_sl_ref,  # [rn, W, 1, hd] — new-key slice for the current new-step
    vn_sl_ref,  # [rn, W, 1, hd]
    kn_full_ref,  # [R, W, 1, hd] — every new key (write-phase patching)
    vn_full_ref,  # [R, W, 1, hd]
    kp_ref,  # [1, 1, ps, hd] — walk page, or the write-target page
    vp_ref,  # [1, 1, ps, hd]
    # outputs
    o_ref,  # [1, 1, W, rep, hd]
    kp_out_ref,  # [1, 1, ps, hd] (aliased with the pool)
    vp_out_ref,  # [1, 1, ps, hd]
    # scratch
    m_scr,  # [W * rep, 1] f32
    l_scr,  # [W * rep, 1] f32
    acc_scr,  # [W * rep, hd] f32
    *,
    sm_scale: float,
    page_size: int,
    num_page_steps: int,
    num_new_steps: int,
    num_write_steps: int,
    num_rows: int,
    rows_per_new_step: int,
    rep: int,
    window: int | None,
):
    r = pl.program_id(0)
    pi = pl.program_id(2)
    ps = page_size
    W = q_ref.shape[2]
    hd = q_ref.shape[4]
    R, rn = num_rows, rows_per_new_step
    q_rows = W * rep
    start = starts_ref[r]
    ctx = ctx_ref[r]
    ntok = ntok_ref[r]
    my_seq = seq_ref[r]

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_iota = jax.lax.broadcasted_iota(jnp.int32, (q_rows, 1), 0) // rep
    q_pos = start + q_iota  # [q_rows, 1] absolute query positions
    q_valid = q_iota < ntok

    def accumulate(s, v):  # s [q_rows, K] f32 (masked), v [K, hd] f32
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # fully-masked rows have m_new == s == -inf; exp(s - m_new) would be
        # 1 there, so re-mask instead of accumulating garbage V
        p = jnp.where(s <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[...] = m_new

    # --- phase A: cached-pool page walk. Pages wholly past the row's cached
    # context contribute nothing; with a sliding window, pages wholly before
    # even the first query's window skip too.
    relevant = (pi < num_page_steps) & (pi * ps < ctx) & (ntok > 0)
    if window is not None:
        relevant &= (pi + 1) * ps - 1 > start - window

    @pl.when(relevant)
    def _pool():
        q = q_ref[0, 0].astype(jnp.float32).reshape(q_rows, hd) * sm_scale
        k = kp_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [q_rows, ps]
        k_pos = pi * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = (k_pos < ctx) & q_valid  # ctx <= start ⇒ causal by construction
        if window is not None:  # HF Mistral semantics (llama.attention_ref)
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)
        accumulate(s, vp_ref[0, 0].astype(jnp.float32))

    # --- phase B: same-launch new keys, one block_n-token row slice per step.
    in_new = (
        (pi >= num_page_steps)
        & (pi < num_page_steps + num_new_steps)
        & (ntok > 0)
    )

    @pl.when(in_new)
    def _new_keys():
        q = q_ref[0, 0].astype(jnp.float32).reshape(q_rows, hd) * sm_scale
        kn = kn_sl_ref[...].astype(jnp.float32).reshape(rn * W, hd)
        st = starts2_ref[...]  # [rn, 1]
        nt = ntok2_ref[...]
        sq = seq2_ref[...]
        jw = jax.lax.broadcasted_iota(jnp.int32, (rn, W), 1)
        k_pos = (st + jw).reshape(1, rn * W)
        k_valid = (jw < nt).reshape(1, rn * W)
        k_seq = jnp.broadcast_to(sq, (rn, W)).reshape(1, rn * W)
        s = jax.lax.dot_general(
            q, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [q_rows, rn * W]
        keep = k_valid & (k_seq == my_seq) & (k_pos <= q_pos) & q_valid
        if window is not None:
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)
        accumulate(s, vn_sl_ref[...].astype(jnp.float32).reshape(rn * W, hd))

    @pl.when(pi == num_page_steps + num_new_steps - 1)
    def _finalize():
        # rows/tokens that never accumulated (padding) divide 0 by the floor
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0, ...] = (acc_scr[...] / l).reshape(W, rep, hd).astype(o_ref.dtype)

    # --- phase C: patch this row's pages in place. Copy-then-patch with ALL
    # launch tokens targeting the page keeps overlapping writes idempotent
    # (module docstring); pages nobody targets copy through unchanged.
    @pl.when(pi >= num_page_steps + num_new_steps)
    def _write():
        wj = pi - (num_page_steps + num_new_steps)
        x = pt_ref[
            r, jnp.clip(start // ps + wj, 0, num_page_steps - 1)
        ]
        match = (tokp_ref[...] == x).reshape(1, R * W)
        slots = toks_ref[...].reshape(1, R * W)
        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (ps, R * W), 0)
        sel = ((slot_iota == slots) & match).astype(jnp.float32)  # [ps, R*W]
        hit = jnp.sum(sel, axis=1, keepdims=True) > 0  # [ps, 1]
        pk = jax.lax.dot_general(
            sel,
            kn_full_ref[...].astype(jnp.float32).reshape(R * W, hd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        pv = jax.lax.dot_general(
            sel,
            vn_full_ref[...].astype(jnp.float32).reshape(R * W, hd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        kp_out_ref[0, 0, ...] = jnp.where(hit, pk.astype(kp_out_ref.dtype), kp_ref[0, 0])
        vp_out_ref[0, 0, ...] = jnp.where(hit, pv.astype(vp_out_ref.dtype), vp_ref[0, 0])


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "window", "block_n", "interpret")
)
def ragged_paged_attention_pallas(
    q: jax.Array,  # [R, W, H, hd]
    k_new: jax.Array,  # [R, W, Kh, hd]
    v_new: jax.Array,  # [R, W, Kh, hd]
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [R, maxp] int32
    row_starts: jax.Array,  # [R] int32
    n_tokens: jax.Array,  # [R] int32 (0 = padding row)
    ctx_lens: jax.Array,  # [R] int32 — keys already in the pool per row
    seq_ids: jax.Array,  # [R] int32 — launch-local sequence identity
    sm_scale: float | None = None,
    window: int | None = None,
    block_n: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns ``(out [R, W, H, hd], k_pages, v_pages)`` with the new K/V
    written in place (the pool operands are aliased)."""
    R, W, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    rn = max(1, min(block_n // W, R))
    R_pad = -(-R // rn) * rn
    if R_pad > R:
        padr = R_pad - R
        q = jnp.pad(q, ((0, padr), (0, 0), (0, 0), (0, 0)))
        k_new = jnp.pad(k_new, ((0, padr), (0, 0), (0, 0), (0, 0)))
        v_new = jnp.pad(v_new, ((0, padr), (0, 0), (0, 0), (0, 0)))
        page_tables = jnp.pad(page_tables, ((0, padr), (0, 0)))
        row_starts = jnp.pad(row_starts, (0, padr))
        n_tokens = jnp.pad(n_tokens, (0, padr))
        ctx_lens = jnp.pad(ctx_lens, (0, padr))
        seq_ids = jnp.pad(seq_ids, (0, padr), constant_values=-1)
    ns = R_pad // rn
    WP = (W + ps - 2) // ps + 1

    # Per-token write targets, precomputed once per launch: page -1 marks
    # tokens that must not write (padding, or positions past the table —
    # the pipelined scheduler's one-step-over-budget dispatch).
    j = jnp.arange(W, dtype=jnp.int32)[None]
    pos = row_starts[:, None] + j
    valid = j < n_tokens[:, None]
    lookup = pos // ps
    in_table = (lookup < maxp) & valid
    tok_pages = jnp.where(
        in_table,
        jnp.take_along_axis(page_tables, jnp.minimum(lookup, maxp - 1), axis=1),
        -1,
    ).astype(jnp.int32)
    tok_slots = jnp.where(in_table, pos % ps, 0).astype(jnp.int32)

    qg = q.reshape(R_pad, W, Kh, rep, hd).transpose(0, 2, 1, 3, 4)
    kernel = functools.partial(
        _ragged_kernel,
        sm_scale=sm_scale,
        page_size=ps,
        num_page_steps=maxp,
        num_new_steps=ns,
        num_write_steps=WP,
        num_rows=R_pad,
        rows_per_new_step=rn,
        rep=rep,
        window=window,
    )

    def _nb(pi):
        return jnp.clip(pi - maxp, 0, ns - 1)

    def _wpage(r, pi, pt, st):
        wj = jnp.clip(pi - (maxp + ns), 0, WP - 1)
        return pt[r, jnp.clip(st[r] // ps + wj, 0, maxp - 1)]

    def _page_in(r, kvh, pi, pt, st, cx, nt, sq):
        walk = pt[r, jnp.minimum(pi, maxp - 1)]
        return (jnp.where(pi < maxp, walk, _wpage(r, pi, pt, st)), kvh, 0, 0)

    def _page_out(r, kvh, pi, pt, st, cx, nt, sq):
        return (_wpage(r, pi, pt, st), kvh, 0, 0)

    page_block = pl.BlockSpec((1, 1, ps, hd), _page_in, memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R_pad, Kh, maxp + ns + WP),
        in_specs=[
            pl.BlockSpec(
                (1, 1, W, rep, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (r, kvh, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (rn, 1),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (rn, 1),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (rn, 1),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (R_pad, W),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (R_pad, W),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (rn, W, 1, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0, kvh, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (rn, W, 1, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0, kvh, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (R_pad, W, 1, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0, kvh, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (R_pad, W, 1, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0, kvh, 0),
                memory_space=pltpu.VMEM,
            ),
            page_block,
            pl.BlockSpec((1, 1, ps, hd), _page_in, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, W, rep, hd),
                lambda r, kvh, pi, pt, st, cx, nt, sq: (r, kvh, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, 1, ps, hd), _page_out, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, ps, hd), _page_out, memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, hd), jnp.float32),
        ],
    )
    starts2 = row_starts[:, None]
    ntok2 = n_tokens[:, None]
    seq2 = seq_ids[:, None]
    out, kp, vp = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((R_pad, Kh, W, rep, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # operand numbering includes the five scalar-prefetch args
        input_output_aliases={15: 1, 16: 2},
        cost_estimate=pl.CostEstimate(
            flops=4 * R_pad * W * H * (maxp * ps + R_pad * W) * hd,
            bytes_accessed=(
                2 * R_pad * (maxp + WP) * Kh * ps * hd * k_pages.dtype.itemsize
            ),
            transcendentals=R_pad * W * H * (maxp * ps + R_pad * W),
        ),
        interpret=interpret,
    )(
        page_tables, row_starts, ctx_lens, n_tokens, seq_ids,
        qg, starts2, ntok2, seq2, tok_pages, tok_slots,
        k_new, v_new, k_new, v_new, k_pages, v_pages,
    )
    out = out.transpose(0, 2, 1, 3, 4).reshape(R_pad, W, H, hd)
    return out[:R], kp, vp
