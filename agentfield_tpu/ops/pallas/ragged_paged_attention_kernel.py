"""Pallas TPU RAGGED paged attention with a fused KV-cache write.

ONE grid program serves every forward shape the engine issues: R ragged
rows of up to W query tokens, each row at its own absolute start over its
own page table — decode rows (n_tokens=1), prefill chunks (a chunk wider
than W splits into several rows sharing a ``seq_id``), DENSE prefill (see
``dense_causal_attention`` below: a fresh prompt is just rows with
``ctx_lens == 0`` over an empty pool), and the speculative verify window
are all just descriptors (see ``ops/paged_attention.py``).
The new K/V ride in as operands and the kernel:

1. walks the row's CACHED pool pages (positions ``< ctx_lens[r]``) with the
   usual online-softmax page stream — pages DMA HBM→VMEM, the gathered
   context never materializes. QUANTIZED pools (int8 / fp8 values +
   per-slot f32 scales, ``ops.kv_quant``) dequantize HERE, inside the
   page-in/accumulate phase: the page tile arrives at half the HBM
   bandwidth and widens to f32 only in VMEM;
2. attends the launch's own new keys (``k_new``) in ``block_n``-token
   slices, masked to the same sequence and causal on absolute positions —
   same-launch keys are NEVER read back from the pool (so quantization
   never degrades intra-launch attention), and the attention pass has no
   read-after-write ordering on the page arrays. Slices whose earliest key
   position lies past the row's last query are skipped wholesale, which
   makes the dense-prefill packing O(S·W) per row instead of O(S²);
3. patches the new K/V into their pool pages in place
   (``input_output_aliases``). Each write step rebuilds a page as
   copy-then-patch-ALL-launch-tokens targeting it, which makes overlapping
   writes IDEMPOTENT: two rows straddling one page (or a torn read of a
   concurrently written page) both produce the identical final content, so
   the multi-row-write restriction of the old per-page patch kernel is
   unrepresentable here. On quantized pools each patched slot quantizes
   with the SHARED formula (``kv_quant.kv_quantize`` inlined) and writes
   its own scale — untouched slots keep their value row and scale
   bit-for-bit, so pages are never materialized in bf16 at any point.

Grid is ``(R, kv_heads, maxp + new_steps + write_steps)``; block sizes come
from ``kernel_autotune`` (``AGENTFIELD_KERNEL_AUTOTUNE``; the table is
keyed by KV dtype too — a quantized page stream amortizes differently).
Padding rows (``n_tokens == 0``) produce zero output and only ever touch
the reserved garbage page 0, whose content is meaningless by contract.

The dense flash-prefill kernel this file's ``dense_causal_attention``
replaced is DELETED (ROADMAP item 4's consolidation): every attention call
in the serving stack now lowers to this one kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from agentfield_tpu.ops.kv_quant import INV_QMAX, SCALE_FLOOR

_NEG_INF = -1e30


def _ragged_kernel(
    # scalar prefetch
    pt_ref,  # [R, maxp] int32
    starts_ref,  # [R] int32
    ctx_ref,  # [R] int32
    ntok_ref,  # [R] int32
    seq_ref,  # [R] int32
    # inputs
    q_ref,  # [1, 1, W, rep, hd] — the (row, kv-head) tile
    starts2_ref,  # [rn, 1] int32 — this new-step's row slice
    ntok2_ref,  # [rn, 1] int32
    seq2_ref,  # [rn, 1] int32
    tokp_ref,  # [R, W] int32 — per-token target page (-1 = no write)
    toks_ref,  # [R, W] int32 — per-token target slot
    kn_sl_ref,  # [rn, W, 1, hd] — new-key slice for the current new-step
    vn_sl_ref,  # [rn, W, 1, hd]
    kn_full_ref,  # [R, W, 1, hd] — every new key (write-phase patching)
    vn_full_ref,  # [R, W, 1, hd]
    kp_ref,  # [1, 1, ps, hd] — walk page, or the write-target page
    vp_ref,  # [1, 1, ps, hd]
    *rest,  # [ksc_ref, vsc_ref] when quantized ([1, 1, ps, 1] scales),
    # then outputs o_ref / kp_out_ref / vp_out_ref [/ ksc_out / vsc_out],
    # then scratch m/l/acc
    sm_scale: float,
    page_size: int,
    num_page_steps: int,
    num_new_steps: int,
    num_write_steps: int,
    num_rows: int,
    rows_per_new_step: int,
    rep: int,
    window: int | None,
    quant: str | None,
):
    if quant is not None:
        (
            ksc_ref, vsc_ref, o_ref, kp_out_ref, vp_out_ref,
            ksc_out_ref, vsc_out_ref, m_scr, l_scr, acc_scr,
        ) = rest
    else:
        ksc_ref = vsc_ref = ksc_out_ref = vsc_out_ref = None
        o_ref, kp_out_ref, vp_out_ref, m_scr, l_scr, acc_scr = rest
    r = pl.program_id(0)
    pi = pl.program_id(2)
    ps = page_size
    W = q_ref.shape[2]
    hd = q_ref.shape[4]
    R, rn = num_rows, rows_per_new_step
    q_rows = W * rep
    start = starts_ref[r]
    ctx = ctx_ref[r]
    ntok = ntok_ref[r]
    my_seq = seq_ref[r]

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_iota = jax.lax.broadcasted_iota(jnp.int32, (q_rows, 1), 0) // rep
    q_pos = start + q_iota  # [q_rows, 1] absolute query positions
    q_valid = q_iota < ntok

    def accumulate(s, v):  # s [q_rows, K] f32 (masked), v [K, hd] f32
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # fully-masked rows have m_new == s == -inf; exp(s - m_new) would be
        # 1 there, so re-mask instead of accumulating garbage V
        p = jnp.where(s <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[...] = m_new

    # --- phase A: cached-pool page walk. Pages wholly past the row's cached
    # context contribute nothing; with a sliding window, pages wholly before
    # even the first query's window skip too.
    relevant = (pi < num_page_steps) & (pi * ps < ctx) & (ntok > 0)
    if window is not None:
        relevant &= (pi + 1) * ps - 1 > start - window

    @pl.when(relevant)
    def _pool():
        q = q_ref[0, 0].astype(jnp.float32).reshape(q_rows, hd) * sm_scale
        k = kp_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        if quant is not None:
            # dequantize in the page-stream phase: per-slot scales [ps, 1]
            # broadcast over head_dim (ops.kv_quant page format)
            k = k * ksc_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [q_rows, ps]
        k_pos = pi * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = (k_pos < ctx) & q_valid  # ctx <= start ⇒ causal by construction
        if window is not None:  # HF Mistral semantics (llama.attention_ref)
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)
        v = vp_ref[0, 0].astype(jnp.float32)
        if quant is not None:
            v = v * vsc_ref[0, 0]
        accumulate(s, v)

    # --- phase B: same-launch new keys, one block_n-token row slice per step.
    # Causal skip: every key in the slice sits at an absolute position >= the
    # slice's earliest valid row start, so a slice starting past the row's
    # LAST query can never be attended — skip the whole step (this is what
    # keeps the dense-prefill packing from paying O(S^2) masked work).
    slice_min_start = jnp.min(
        jnp.where(ntok2_ref[...] > 0, starts2_ref[...], jnp.int32(2**30))
    )
    in_new = (
        (pi >= num_page_steps)
        & (pi < num_page_steps + num_new_steps)
        & (ntok > 0)
        & (slice_min_start <= start + W - 1)
    )

    @pl.when(in_new)
    def _new_keys():
        q = q_ref[0, 0].astype(jnp.float32).reshape(q_rows, hd) * sm_scale
        kn = kn_sl_ref[...].astype(jnp.float32).reshape(rn * W, hd)
        st = starts2_ref[...]  # [rn, 1]
        nt = ntok2_ref[...]
        sq = seq2_ref[...]
        jw = jax.lax.broadcasted_iota(jnp.int32, (rn, W), 1)
        k_pos = (st + jw).reshape(1, rn * W)
        k_valid = (jw < nt).reshape(1, rn * W)
        k_seq = jnp.broadcast_to(sq, (rn, W)).reshape(1, rn * W)
        s = jax.lax.dot_general(
            q, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [q_rows, rn * W]
        keep = k_valid & (k_seq == my_seq) & (k_pos <= q_pos) & q_valid
        if window is not None:
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)
        accumulate(s, vn_sl_ref[...].astype(jnp.float32).reshape(rn * W, hd))

    @pl.when(pi == num_page_steps + num_new_steps - 1)
    def _finalize():
        # rows/tokens that never accumulated (padding) divide 0 by the floor
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0, ...] = (acc_scr[...] / l).reshape(W, rep, hd).astype(o_ref.dtype)

    # --- phase C: patch this row's pages in place. Copy-then-patch with ALL
    # launch tokens targeting the page keeps overlapping writes idempotent
    # (module docstring); pages nobody targets copy through unchanged.
    @pl.when(pi >= num_page_steps + num_new_steps)
    def _write():
        wj = pi - (num_page_steps + num_new_steps)
        x = pt_ref[
            r, jnp.clip(start // ps + wj, 0, num_page_steps - 1)
        ]
        match = (tokp_ref[...] == x).reshape(1, R * W)
        slots = toks_ref[...].reshape(1, R * W)
        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (ps, R * W), 0)
        sel = ((slot_iota == slots) & match).astype(jnp.float32)  # [ps, R*W]
        hit = jnp.sum(sel, axis=1, keepdims=True) > 0  # [ps, 1]
        pk = jax.lax.dot_general(
            sel,
            kn_full_ref[...].astype(jnp.float32).reshape(R * W, hd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        pv = jax.lax.dot_general(
            sel,
            vn_full_ref[...].astype(jnp.float32).reshape(R * W, hd),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quant is not None:
            # per-slot quantization, the SHARED formula (kv_quant.kv_quantize
            # inlined — identical ops keep the fused write bit-exact vs the
            # XLA reference scatter). Untouched slots keep value + scale.
            inv_qmax = INV_QMAX[quant]
            sk = jnp.maximum(jnp.max(jnp.abs(pk), axis=1) * inv_qmax, SCALE_FLOOR)
            sv = jnp.maximum(jnp.max(jnp.abs(pv), axis=1) * inv_qmax, SCALE_FLOOR)
            yk = pk / sk[:, None]
            yv = pv / sv[:, None]
            if quant == "int8":
                qk = jnp.clip(jnp.round(yk), -127.0, 127.0)
                qv = jnp.clip(jnp.round(yv), -127.0, 127.0)
            else:
                qk, qv = yk, yv
            kp_out_ref[0, 0, ...] = jnp.where(
                hit, qk.astype(kp_out_ref.dtype), kp_ref[0, 0]
            )
            vp_out_ref[0, 0, ...] = jnp.where(
                hit, qv.astype(vp_out_ref.dtype), vp_ref[0, 0]
            )
            ksc_out_ref[0, 0, ...] = jnp.where(hit, sk[:, None], ksc_ref[0, 0])
            vsc_out_ref[0, 0, ...] = jnp.where(hit, sv[:, None], vsc_ref[0, 0])
        else:
            kp_out_ref[0, 0, ...] = jnp.where(
                hit, pk.astype(kp_out_ref.dtype), kp_ref[0, 0]
            )
            vp_out_ref[0, 0, ...] = jnp.where(
                hit, pv.astype(vp_out_ref.dtype), vp_ref[0, 0]
            )


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "window", "block_n", "interpret")
)
def ragged_paged_attention_pallas(
    q: jax.Array,  # [R, W, H, hd]
    k_new: jax.Array,  # [R, W, Kh, hd]
    v_new: jax.Array,  # [R, W, Kh, hd]
    k_pages: jax.Array,  # [P, Kh, ps, hd] (bf16/f32, or int8/fp8 when quantized)
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [R, maxp] int32
    row_starts: jax.Array,  # [R] int32
    n_tokens: jax.Array,  # [R] int32 (0 = padding row)
    ctx_lens: jax.Array,  # [R] int32 — keys already in the pool per row
    seq_ids: jax.Array,  # [R] int32 — launch-local sequence identity
    k_scales: jax.Array | None = None,  # [P, Kh, ps] f32 per-slot scales
    v_scales: jax.Array | None = None,  # (both or neither; ops.kv_quant)
    sm_scale: float | None = None,
    window: int | None = None,
    block_n: int = 128,
    interpret: bool = False,
):
    """Returns ``(out [R, W, H, hd], k_pages, v_pages)`` — plus
    ``(k_scales, v_scales)`` when a quantized pool's scales were passed —
    with the new K/V written in place (the pool operands are aliased)."""
    R, W, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together")
    quant: str | None = None
    if k_scales is not None:
        quant = "int8" if k_pages.dtype == jnp.int8 else "fp8"
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    rn = max(1, min(block_n // W, R))
    R_pad = -(-R // rn) * rn
    if R_pad > R:
        padr = R_pad - R
        q = jnp.pad(q, ((0, padr), (0, 0), (0, 0), (0, 0)))
        k_new = jnp.pad(k_new, ((0, padr), (0, 0), (0, 0), (0, 0)))
        v_new = jnp.pad(v_new, ((0, padr), (0, 0), (0, 0), (0, 0)))
        page_tables = jnp.pad(page_tables, ((0, padr), (0, 0)))
        row_starts = jnp.pad(row_starts, (0, padr))
        n_tokens = jnp.pad(n_tokens, (0, padr))
        ctx_lens = jnp.pad(ctx_lens, (0, padr))
        seq_ids = jnp.pad(seq_ids, (0, padr), constant_values=-1)
    ns = R_pad // rn
    WP = (W + ps - 2) // ps + 1

    # Per-token write targets, precomputed once per launch: page -1 marks
    # tokens that must not write (padding, or positions past the table —
    # the pipelined scheduler's one-step-over-budget dispatch).
    j = jnp.arange(W, dtype=jnp.int32)[None]
    pos = row_starts[:, None] + j
    valid = j < n_tokens[:, None]
    lookup = pos // ps
    in_table = (lookup < maxp) & valid
    tok_pages = jnp.where(
        in_table,
        jnp.take_along_axis(page_tables, jnp.minimum(lookup, maxp - 1), axis=1),
        -1,
    ).astype(jnp.int32)
    tok_slots = jnp.where(in_table, pos % ps, 0).astype(jnp.int32)

    qg = q.reshape(R_pad, W, Kh, rep, hd).transpose(0, 2, 1, 3, 4)
    kernel = functools.partial(
        _ragged_kernel,
        sm_scale=sm_scale,
        page_size=ps,
        num_page_steps=maxp,
        num_new_steps=ns,
        num_write_steps=WP,
        num_rows=R_pad,
        rows_per_new_step=rn,
        rep=rep,
        window=window,
        quant=quant,
    )

    def _nb(pi):
        return jnp.clip(pi - maxp, 0, ns - 1)

    def _wpage(r, pi, pt, st):
        wj = jnp.clip(pi - (maxp + ns), 0, WP - 1)
        return pt[r, jnp.clip(st[r] // ps + wj, 0, maxp - 1)]

    def _page_in(r, kvh, pi, pt, st, cx, nt, sq):
        walk = pt[r, jnp.minimum(pi, maxp - 1)]
        return (jnp.where(pi < maxp, walk, _wpage(r, pi, pt, st)), kvh, 0, 0)

    def _page_out(r, kvh, pi, pt, st, cx, nt, sq):
        return (_wpage(r, pi, pt, st), kvh, 0, 0)

    in_specs = [
        pl.BlockSpec(
            (1, 1, W, rep, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (r, kvh, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (rn, 1),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (rn, 1),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (rn, 1),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (R_pad, W),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (R_pad, W),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (rn, W, 1, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0, kvh, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (rn, W, 1, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (_nb(pi), 0, kvh, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (R_pad, W, 1, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0, kvh, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (R_pad, W, 1, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (0, 0, kvh, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec((1, 1, ps, hd), _page_in, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, ps, hd), _page_in, memory_space=pltpu.VMEM),
    ]
    out_specs = [
        pl.BlockSpec(
            (1, 1, W, rep, hd),
            lambda r, kvh, pi, pt, st, cx, nt, sq: (r, kvh, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec((1, 1, ps, hd), _page_out, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, ps, hd), _page_out, memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((R_pad, Kh, W, rep, hd), q.dtype),
        jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
        jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
    ]
    # operand numbering includes the five scalar-prefetch args
    aliases = {15: 1, 16: 2}
    operands = [
        qg, row_starts[:, None], n_tokens[:, None], seq_ids[:, None],
        tok_pages, tok_slots, k_new, v_new, k_new, v_new, k_pages, v_pages,
    ]
    if quant is not None:
        # Scales ride as [P, Kh, ps, 1] so the (ps, 1) block tail is made of
        # full array dims (same Mosaic tiling rationale as the page layout);
        # [ps, 1] also broadcasts directly against the [ps, hd] value tile.
        sc_spec = pl.BlockSpec((1, 1, ps, 1), _page_in, memory_space=pltpu.VMEM)
        sc_out = pl.BlockSpec((1, 1, ps, 1), _page_out, memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        out_specs += [sc_out, sc_out]
        sc_shape = jax.ShapeDtypeStruct((P, Kh, ps, 1), jnp.float32)
        out_shape += [sc_shape, sc_shape]
        aliases = {15: 1, 16: 2, 17: 3, 18: 4}
        operands += [
            k_scales.reshape(P, Kh, ps, 1).astype(jnp.float32),
            v_scales.reshape(P, Kh, ps, 1).astype(jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R_pad, Kh, maxp + ns + WP),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, hd), jnp.float32),
        ],
    )
    results = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        cost_estimate=pl.CostEstimate(
            flops=4 * R_pad * W * H * (maxp * ps + R_pad * W) * hd,
            bytes_accessed=(
                2 * R_pad * (maxp + WP) * Kh * ps * hd * k_pages.dtype.itemsize
            ),
            transcendentals=R_pad * W * H * (maxp * ps + R_pad * W),
        ),
        interpret=interpret,
    )(
        page_tables, row_starts, ctx_lens, n_tokens, seq_ids,
        *operands,
    )
    out = results[0].transpose(0, 2, 1, 3, 4).reshape(R_pad, W, H, hd)[:R]
    if quant is not None:
        kp, vp, ksc, vsc = results[1:5]
        return out, kp, vp, ksc.reshape(P, Kh, ps), vsc.reshape(P, Kh, ps)
    return out, results[1], results[2]


def dense_causal_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, Kh, hd]
    v: jax.Array,  # [B, S, Kh, hd]
    window: int | None = None,
    sm_scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Dense causal self-attention through the ONE ragged kernel — the
    replacement for the deleted standalone flash-prefill kernel
    (``EngineConfig.prefill_impl="flash"`` resolves here; docs/KERNELS.md).

    Each batch row packs as ``ceil(S / block_q)`` same-``seq_id`` ragged
    rows over an EMPTY one-page pool (``ctx_lens == 0`` — the page walk
    never fires), so the whole computation runs in the kernel's same-launch
    new-key phase: online-softmax over ``block_n``-token key slices with
    causal skipping, exactly the flash recurrence, in the same grid program
    decode and chunk prefill ride. Writes land on the reserved garbage page
    (a dense prefill has no pool to fill — the engine scatters K/V into
    real pages itself); the 128-slot dummy page bounds that write phase at
    ~ceil(block_q/128)+1 steps, a few percent of the attention FLOPs at the
    engine's launch sizes.

    Operating envelope: the kernel's new-key operands hold the WHOLE
    launch's ``B*S`` K/V in VMEM (its ``kn_full`` blocks are per-launch,
    not per-tile), so very long dense sequences must be chunked BEFORE
    this call — the engine already does this (``prefill_impl="flash"``
    auto-resolves ``prefill_chunk=512``, so no dense launch exceeds a
    512-token bucket; at B=8, S=512, hd=128, bf16 that is ~2MB of new-KV
    VMEM). Standalone callers with S in the thousands should route through
    the chunked/paged path instead. Returns ``[B, S, H, hd]``."""
    from agentfield_tpu.ops.pallas.kernel_autotune import lookup_blocks

    B, S, H, hd = q.shape
    Kh = k.shape[2]
    blocks = lookup_blocks(page_size=128, head_dim=hd, bucket=S)
    W = max(1, min(blocks.block_q, S))
    nw = -(-S // W)
    S_pad = nw * W
    if S_pad > S:
        padn = S_pad - S
        q = jnp.pad(q, ((0, 0), (0, padn), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, padn), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, padn), (0, 0), (0, 0)))
    R = B * nw
    qr = q.reshape(R, W, H, hd)
    kr = k.reshape(R, W, Kh, hd)
    vr = v.reshape(R, W, Kh, hd)
    starts = jnp.tile(jnp.arange(nw, dtype=jnp.int32) * W, B)
    n_toks = jnp.tile(
        jnp.clip(S - jnp.arange(nw, dtype=jnp.int32) * W, 0, W), B
    )
    seqs = jnp.repeat(jnp.arange(B, dtype=jnp.int32), nw)
    ctx = jnp.zeros((R,), jnp.int32)
    tables = jnp.zeros((R, 1), jnp.int32)
    # 128-slot dummy page: its only job is bounding the write-phase step
    # count (WP ≈ W/ps); page 0 is the garbage sink by contract.
    pool = jnp.zeros((1, Kh, 128, hd), q.dtype)
    out, _, _ = ragged_paged_attention_pallas(
        qr, kr, vr, pool, pool, tables, starts, n_toks, ctx, seqs,
        sm_scale=sm_scale, window=window, block_n=blocks.block_n,
        interpret=interpret,
    )
    return out.reshape(B, S_pad, H, hd)[:, :S]
