"""Pallas TPU BATCHED paged chunk attention (speculative verify forward).

Every row of a [B, W] verify window (speculative decoding: W = spec_k+1
tokens per sequence, each row at its OWN start position) attends over its
sequence's paged K/V. The single-sequence chunk kernel
(paged_chunk_attention_kernel.py) covers suffix/chunked prefill; the
speculative verify is a *batch* of small ragged chunks, which previously
fell back to the per-layer full-context gather (engine `_spec_decode_fn`
verify body materialized [B, T, Kh, hd] per layer per step — exactly the
bandwidth the paged kernels exist to avoid).

Same online-softmax page walk as the decode kernel, widened to W query rows
per sequence and indexed per-batch-row through scalar-prefetched page
tables. Pages wholly past a row's keys (or wholly before its sliding
window) are skipped.

The mixed token-budget scheduler (docs/MIXED_SCHEDULING.md) drives this
kernel at W=1: every packed token — a decode token or one token of a
prefill chunk — is its own n_tokens=1 ragged row with its own page table,
start and key count. W=1 rows are the cheap corner of the row loop: the
q/o block collapses to (1, 1, 1, rep, hd), the scratch accumulator to
(rep, hd), and the per-page `relevant` predicate skips every page past the
row's keys, so a decode row touches exactly ceil((start+1)/ps) pages — the
same page traffic as the dedicated decode kernel, with no W-wide padding
compute. ``paged_batch_chunk_attention_ref`` below is the XLA reference
for parity tests and CPU/debug fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def paged_batch_chunk_attention_ref(
    q: jax.Array,  # [B, W, H, hd] — W query tokens per sequence
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, maxp] int32
    starts: jax.Array,  # [B] int32 — absolute position of q[:, 0]
    k_lens: jax.Array,  # [B] int32 — valid keys per row (0 = inactive row)
    sm_scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """XLA reference for the batched ragged chunk kernel: gathers each row's
    pages into [B, T] context and runs masked-softmax attention. Semantics
    match the kernel exactly — per-row causal masking against absolute
    positions, sliding window, and zeros for inactive (k_lens == 0) rows —
    so it serves both as the parity oracle in tests and as the engine's
    chunk-attention path on backends without the kernel."""
    B, W, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    T = maxp * ps
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5
    # [B, maxp, Kh, ps, hd] → [B, T, Kh, hd]
    k = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)
    v = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)
    qg = q.reshape(B, W, Kh, rep, hd)
    logits = jnp.einsum(
        "bwkrh,btkh->bkrwt", qg, k, preferred_element_type=jnp.float32
    ) * sm_scale
    k_pos = jnp.arange(T, dtype=jnp.int32)[None, None]  # [1, 1, T]
    q_pos = starts[:, None] + jnp.arange(W, dtype=jnp.int32)[None]  # [B, W]
    keep = (k_pos <= q_pos[..., None]) & (k_pos < k_lens[:, None, None])
    if window is not None:  # HF Mistral semantics (attention_ref)
        keep = keep & (k_pos > q_pos[..., None] - window)
    logits = jnp.where(keep[:, None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bkrwt,btkh->bwkrh", probs, v, preferred_element_type=jnp.float32
    ).reshape(B, W, H, hd)
    # inactive rows return zeros like the kernel's un-accumulated finalize
    return jnp.where((k_lens > 0)[:, None, None, None], out, 0.0).astype(q.dtype)


def _batch_chunk_kernel(
    page_tables_ref,  # [B, maxp] int32 (scalar prefetch)
    starts_ref,  # [B] int32 — absolute position of each row's first query
    k_lens_ref,  # [B] int32 — total valid keys per row (start + W; 0 = inactive)
    q_ref,  # [1, 1, W, rep, hd] — the (batch, kv-head) tile
    k_ref,  # [1, 1, ps, hd]
    v_ref,  # [1, 1, ps, hd]
    o_ref,  # [1, 1, W, rep, hd]
    m_scr,  # [W * rep, 1] f32
    l_scr,  # [W * rep, 1] f32
    acc_scr,  # [W * rep, hd] f32
    *,
    sm_scale: float,
    page_size: int,
    num_page_steps: int,
    rep: int,
    window: int | None,
):
    b = pl.program_id(0)
    pi = pl.program_id(2)
    start = starts_ref[b]
    k_len = k_lens_ref[b]
    W = q_ref.shape[2]

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    relevant = pi * page_size < k_len
    if window is not None:
        # pages wholly before even the FIRST query's window skip
        relevant &= (pi + 1) * page_size - 1 > start - window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32).reshape(W * rep, -1) * sm_scale
        k = k_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [W*rep, ps]
        k_pos = pi * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        q_pos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep
        keep = (k_pos <= q_pos) & (k_pos < k_len)
        if window is not None:  # HF Mistral semantics (attention_ref)
            keep &= k_pos > q_pos - window
        s = jnp.where(keep, s, _NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(pi == num_page_steps - 1)
    def _finalize():
        # inactive rows (k_len 0) never accumulated: the l floor yields 0s
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0, ...] = (acc_scr[...] / l).reshape(W, rep, -1).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret", "window"))
def paged_batch_chunk_attention_pallas(
    q: jax.Array,  # [B, W, H, hd] — W query tokens per sequence
    k_pages: jax.Array,  # [P, Kh, ps, hd]
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, maxp] int32
    starts: jax.Array,  # [B] int32 — absolute position of q[:, 0]
    k_lens: jax.Array,  # [B] int32 — valid keys per row (0 = inactive row)
    sm_scale: float | None = None,
    interpret: bool = False,
    window: int | None = None,  # sliding window on absolute positions
) -> jax.Array:
    """Returns [B, W, H, hd]. Inactive rows (k_lens == 0) return zeros."""
    B, W, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    qg = q.reshape(B, W, Kh, rep, hd).transpose(0, 2, 1, 3, 4)  # [B, Kh, W, rep, hd]
    kernel = functools.partial(
        _batch_chunk_kernel, sm_scale=sm_scale, page_size=ps, num_page_steps=maxp,
        rep=rep, window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Kh, maxp),
        in_specs=[
            pl.BlockSpec(
                (1, 1, W, rep, hd), lambda b, kvh, pi, pt, st, kl: (b, kvh, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, ps, hd), lambda b, kvh, pi, pt, st, kl: (pt[b, pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, ps, hd), lambda b, kvh, pi, pt, st, kl: (pt[b, pi], kvh, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, W, rep, hd), lambda b, kvh, pi, pt, st, kl: (b, kvh, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, 1), jnp.float32),
            pltpu.VMEM((W * rep, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kh, W, rep, hd), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * W * H * maxp * ps * hd,
            bytes_accessed=2 * B * maxp * ps * Kh * hd * k_pages.dtype.itemsize,
            transcendentals=B * W * H * maxp * ps,
        ),
        interpret=interpret,
    )(page_tables, starts, k_lens, qg, k_pages, v_pages)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, W, H, hd)
