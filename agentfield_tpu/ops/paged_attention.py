"""Ragged paged attention: the ONE attention entry point for serving.

Every forward the engine issues — classic span decode, the mixed
token-budget tick, chunked/suffix prefill, the speculative verify window —
is a batch of *ragged rows*: R rows of up to W query tokens each, every row
at its own start position over its own page table, with its own count of
already-cached keys. ``ragged_paged_attention`` consumes that descriptor
directly and FUSES the KV-cache write: each row's new K/V land in the paged
pool in the same dispatch that attends over them (same-launch keys are
served from the ``k_new``/``v_new`` operands, so the kernel never reads its
own writes). This replaces the four special-case kernels the engine used to
route between (decode, chunk, batch-chunk, kv-write) — and the scheduler
special-cases that existed only because the per-page patch kernel could not
take multi-row writes. See docs/KERNELS.md.

Two implementations:

- ``ragged_paged_attention_ref`` — XLA: exact multi-row scatter into the
  pool, then a page-gather masked attention (materializes [R, max_ctx] K/V
  in HBM; correct everywhere incl. CPU tests; bandwidth-wasteful).
- ``ops/pallas/ragged_paged_attention_kernel.py`` — Pallas TPU kernel that
  streams pages HBM→VMEM per row and patches pool pages in place; block
  sizes come from the autotable (``ops/pallas/kernel_autotune.py``,
  ``AGENTFIELD_KERNEL_AUTOTUNE``, keyed by KV dtype). Runs in the Pallas
  interpreter on CPU.

Pool operands may be plain arrays or ``ops.kv_quant.QuantPages`` (int8/fp8
values + per-slot scales, ``EngineConfig.kv_quant_dtype``): both impls
dequantize cached pages on the way in and quantize new K/V on the way out
with the shared ``kv_quantize`` formula, and the dispatcher repacks the
pytree — callers carry one pool operand either way (docs/KERNELS.md
"Quantized pages").

The row descriptor (``RaggedRows``) is produced by
``serving.kv_cache.pack_ragged_rows``; its invariants:

- row r's queries sit at absolute positions ``[row_starts[r],
  row_starts[r] + n_tokens[r])``; ``n_tokens[r] == 0`` marks a padding row
  (zero output, no writes).
- ``ctx_lens[r]`` keys for the row's sequence are already in the pool;
  positions ``[ctx_lens[r], row_starts[r])`` are covered by EARLIER rows of
  the same launch carrying the same ``seq_ids[r]`` (a chunk wider than W
  splits into several rows).
- pages are looked up as ``page_tables[r, pos // page_size]``; positions at
  or past ``max_pages * page_size`` route to the reserved garbage page 0.
"""

from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


class RaggedRows(typing.NamedTuple):
    """Host-side ragged forward descriptor (one kernel launch)."""

    tokens: typing.Any  # [R, W] int32 token ids (model input, not consumed here)
    page_tables: typing.Any  # [R, maxp] int32
    row_starts: typing.Any  # [R] int32 — absolute position of row r's first query
    n_tokens: typing.Any  # [R] int32 — valid queries in row r (0 = padding row)
    ctx_lens: typing.Any  # [R] int32 — keys already in the pool for row r's seq
    seq_ids: typing.Any  # [R] int32 — launch-local sequence identity (-1 padding)
    last_flat: list  # flat token index of each packed entry's LAST token


def ragged_paged_attention_ref(
    q: jax.Array,  # [R, W, H, hd]
    k_new: jax.Array,  # [R, W, Kh, hd] — new K per query token (pre-write)
    v_new: jax.Array,  # [R, W, Kh, hd]
    k_pages: jax.Array,  # [P, Kh, ps, hd] (int8/fp8 when scales are passed)
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [R, maxp] int32
    row_starts: jax.Array,  # [R] int32
    n_tokens: jax.Array,  # [R] int32
    ctx_lens: jax.Array,  # [R] int32 (unused by the ref: the scatter-first
    # pool already holds same-launch keys; kept for signature parity)
    seq_ids: jax.Array,  # [R] int32 (unused by the ref, same reason)
    k_scales: jax.Array | None = None,  # [P, Kh, ps] f32 per-slot scales
    v_scales: jax.Array | None = None,  # (quantized pools; ops.kv_quant)
    sm_scale: float | None = None,
    window: int | None = None,
):
    """XLA reference: exact multi-row scatter of the new K/V into the paged
    pool, then masked gather attention per row. Returns
    ``(out [R, W, H, hd], k_pages, v_pages)`` — plus ``(k_scales,
    v_scales)`` when a quantized pool's scales were passed. Semantics match
    the Pallas kernel exactly — per-row causal masking on absolute
    positions, sliding window, zeros for padding rows/tokens; on quantized
    pools the scatter quantizes per slot with the SHARED
    ``kv_quant.kv_quantize`` formula, so even the stored bytes are
    bit-identical to the fused kernel's — and it serves as the parity
    oracle in tests AND as the engine's attention on backends without the
    kernel. One honest divergence under quantization: the kernel attends
    same-launch keys pre-quantization (they never round-trip the pool)
    while this gather reads them back quantized — the parity battery pins
    that gap inside the per-dtype error bound."""
    del ctx_lens, seq_ids
    from agentfield_tpu.ops.kv_quant import kv_quantize

    R, W, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    T = maxp * ps
    if H % Kh:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {Kh}")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together")
    quant = None
    if k_scales is not None:
        quant = "int8" if k_pages.dtype == jnp.int8 else "fp8"
    rep = H // Kh
    if sm_scale is None:
        sm_scale = hd**-0.5

    j = jnp.arange(W, dtype=jnp.int32)[None]  # [1, W]
    pos = row_starts[:, None] + j  # [R, W]
    valid = j < n_tokens[:, None]  # [R, W]
    lookup = pos // ps
    in_table = (lookup < maxp) & valid
    page_ids = jnp.where(
        in_table,
        jnp.take_along_axis(page_tables, jnp.minimum(lookup, maxp - 1), axis=1),
        0,
    )  # [R, W] — padding/over-budget tokens write the garbage page
    slot_ids = pos % ps
    # Multi-row scatter: advanced [R, W] indices at dims 0,2 of
    # [P, Kh, ps, hd] put the broadcast dims first → values [R, W, Kh, hd].
    if quant is not None:
        kq, ks = kv_quantize(k_new, quant)
        vq, vs = kv_quantize(v_new, quant)
        k_pages = k_pages.at[page_ids, :, slot_ids].set(kq)
        v_pages = v_pages.at[page_ids, :, slot_ids].set(vq)
        k_scales = k_scales.at[page_ids, :, slot_ids].set(ks)
        v_scales = v_scales.at[page_ids, :, slot_ids].set(vs)
    else:
        k_pages = k_pages.at[page_ids, :, slot_ids].set(k_new.astype(k_pages.dtype))
        v_pages = v_pages.at[page_ids, :, slot_ids].set(v_new.astype(v_pages.dtype))

    # [R, maxp, Kh, ps, hd] → [R, T, Kh, hd] gathered context (now holding
    # this launch's keys too — the mask below only ever admits key positions
    # the launch has actually populated). Quantized pools dequantize in the
    # gather: values * per-slot scales, f32; plain pools gather in the page
    # dtype (the einsums upcast exactly, so the none-mode is bit-unchanged).
    if quant is not None:
        k = k_pages[page_tables].astype(jnp.float32) * k_scales[page_tables][..., None]
        v = v_pages[page_tables].astype(jnp.float32) * v_scales[page_tables][..., None]
    else:
        k = k_pages[page_tables]
        v = v_pages[page_tables]
    k = k.transpose(0, 1, 3, 2, 4).reshape(R, T, Kh, hd)
    v = v.transpose(0, 1, 3, 2, 4).reshape(R, T, Kh, hd)
    qg = q.reshape(R, W, Kh, rep, hd)
    logits = jnp.einsum(
        "bwkrh,btkh->bkrwt", qg, k, preferred_element_type=jnp.float32
    ) * sm_scale
    k_pos = jnp.arange(T, dtype=jnp.int32)[None, None]  # [1, 1, T]
    keep = (k_pos <= pos[..., None]) & valid[..., None]  # [R, W, T]
    if window is not None:  # HF Mistral semantics (llama.attention_ref)
        keep = keep & (k_pos > pos[..., None] - window)
    logits = jnp.where(keep[:, None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bkrwt,btkh->bwkrh", probs, v, preferred_element_type=jnp.float32
    ).reshape(R, W, H, hd)
    # padding rows/tokens return zeros like the kernel's un-accumulated rows
    out = jnp.where(valid[..., None, None], out, 0.0).astype(q.dtype)
    if quant is not None:
        return out, k_pages, v_pages, k_scales, v_scales
    return out, k_pages, v_pages


def ragged_paged_attention(
    q,
    k_new,
    v_new,
    k_pages,
    v_pages,
    page_tables,
    row_starts,
    n_tokens,
    ctx_lens,
    seq_ids,
    impl: str = "ref",
    mesh=None,
    window: int | None = None,
    sm_scale: float | None = None,
):
    """Dispatch one ragged fused write+attention launch.

    ``k_pages``/``v_pages`` are plain arrays (bf16/f32 pools) or
    :class:`ops.kv_quant.QuantPages` (int8/fp8 values + per-slot scales —
    ``EngineConfig.kv_quant_dtype``); the quantized representation flows
    through both impls and back out as the same pytree, so callers carry
    ONE pool operand either way.

    With `mesh` (tensor parallelism) the Pallas kernel runs under shard_map
    over the KV-head axis: each shard owns its slice of the page pool and
    its heads' queries/new-KV ([.., Kh/tp, ..] — matching wk/wv's TP
    sharding) and computes with NO collectives; the psum over the output
    projection downstream is the only cross-chip traffic, exactly as in the
    ref GSPMD path (XLA partitions the scatter+gather itself)."""
    from agentfield_tpu.ops.kv_quant import QuantPages, quant_mode_of

    quant = isinstance(k_pages, QuantPages)
    kq, ksc = (k_pages.q, k_pages.scale) if quant else (k_pages, None)
    vq, vsc = (v_pages.q, v_pages.scale) if quant else (v_pages, None)
    if impl == "ref":
        out = ragged_paged_attention_ref(
            q, k_new, v_new, kq, vq, page_tables, row_starts,
            n_tokens, ctx_lens, seq_ids, k_scales=ksc, v_scales=vsc,
            sm_scale=sm_scale, window=window,
        )
    elif impl != "pallas":
        raise ValueError(f"unknown ragged_paged_attention impl {impl!r}")
    else:
        from agentfield_tpu.ops.pallas.ragged_paged_attention_kernel import (
            ragged_paged_attention_pallas,
        )
        from agentfield_tpu.ops.pallas.kernel_autotune import lookup_blocks

        blocks = lookup_blocks(
            page_size=kq.shape[2],
            head_dim=kq.shape[3],
            bucket=q.shape[0] * q.shape[1],
            kv_dtype=quant_mode_of(k_pages),
        )
        # Mosaic kernels only compile for TPU; on CPU backends (tests, local
        # demos) run the same kernel in the Pallas interpreter.
        interpret = jax.default_backend() == "cpu"
        import functools

        fn = functools.partial(
            ragged_paged_attention_pallas,
            sm_scale=sm_scale,
            window=window,
            block_n=blocks.block_n,
            interpret=interpret,
        )
        if quant:
            base = fn
            fn = lambda q_, kn, vn, kp, vp, pt, rs, nt, cx, sq, ks_, vs_: base(  # noqa: E731
                q_, kn, vn, kp, vp, pt, rs, nt, cx, sq,
                k_scales=ks_, v_scales=vs_,
            )
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            from agentfield_tpu.parallel.mesh import AXIS_MODEL
            from agentfield_tpu.parallel.mesh import shard_map  # version compat

            if mesh.shape.get(AXIS_MODEL, 1) > 1:
                in_specs = [
                    P(None, None, AXIS_MODEL, None),  # q [R, W, H, hd]
                    P(None, None, AXIS_MODEL, None),  # k_new [R, W, Kh, hd]
                    P(None, None, AXIS_MODEL, None),  # v_new
                    P(None, AXIS_MODEL, None, None),  # pages on Kh
                    P(None, AXIS_MODEL, None, None),
                    P(None, None),  # page_tables replicated
                    P(None), P(None), P(None), P(None),
                ]
                out_specs = [
                    P(None, None, AXIS_MODEL, None),
                    P(None, AXIS_MODEL, None, None),
                    P(None, AXIS_MODEL, None, None),
                ]
                if quant:
                    # scales shard with their pages on the Kh axis
                    in_specs += [P(None, AXIS_MODEL, None), P(None, AXIS_MODEL, None)]
                    out_specs += [P(None, AXIS_MODEL, None), P(None, AXIS_MODEL, None)]
                fn = shard_map(
                    fn, mesh=mesh,
                    in_specs=tuple(in_specs), out_specs=tuple(out_specs),
                )
        args = [
            q, k_new, v_new, kq, vq, page_tables, row_starts,
            n_tokens, ctx_lens, seq_ids,
        ]
        if quant:
            args += [ksc, vsc]
        out = fn(*args)
    if quant:
        o, kp, vp, ks_, vs_ = out
        return o, QuantPages(kp, ks_), QuantPages(vp, vs_)
    return out


# ---------------------------------------------------------------------------
# Legacy single-purpose entry points (deprecated shims — one release).
# ``paged_attention_ref`` stays a real implementation: tests use it as an
# independent decode oracle. The dispatchers below now ride the ragged path.
# ---------------------------------------------------------------------------


def paged_attention_ref(
    q: jax.Array,  # [B, H, hd]       — one query token per sequence
    k_pages: jax.Array,  # [P, Kh, ps, hd]  — one layer's page pool
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [B, maxp] int32 page ids (0 = garbage page)
    seq_lens: jax.Array,  # [B] int32 — #valid tokens (incl. current) per sequence
    window: int | None = None,  # sliding window (Mistral): the query (at
    # position seq_len-1) attends keys within the most recent `window` only
) -> jax.Array:
    """Single-token decode attention via page gather (the pre-ragged decode
    reference, kept as an independent oracle). Returns [B, H, hd]."""
    B, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    T = maxp * ps

    # [B, maxp, Kh, ps, hd] → [B, T, Kh, hd]
    k = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)
    v = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)

    rep = H // Kh
    qg = q.reshape(B, Kh, rep, hd)
    logits = jnp.einsum("bkrh,btkh->bkrt", qg, k, preferred_element_type=jnp.float32)
    logits = logits * (hd ** -0.5)
    k_pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = k_pos < seq_lens[:, None]  # [B, T]
    if window is not None:
        valid = valid & (k_pos >= seq_lens[:, None] - window)
    logits = jnp.where(valid[:, None, None, :], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkrt,btkh->bkrh", probs, v, preferred_element_type=jnp.float32)
    return out.reshape(B, H, hd).astype(q.dtype)


def paged_attention(
    q, k_pages, v_pages, page_tables, seq_lens, impl: str = "ref", mesh=None,
    window: int | None = None,
):
    """DEPRECATED: decode-only dispatch over a pre-written pool. Use
    ``ragged_paged_attention`` (fused write + any n_tokens mix). Kept one
    release for out-of-tree callers; both impls resolve to the XLA
    reference."""
    import warnings

    warnings.warn(
        "ops.paged_attention.paged_attention is deprecated; use "
        "ragged_paged_attention (fused ragged kernel)",
        DeprecationWarning,
        stacklevel=2,
    )
    if impl not in ("ref", "pallas"):
        raise ValueError(f"unknown paged_attention impl {impl!r}")
    return paged_attention_ref(
        q, k_pages, v_pages, page_tables, seq_lens, window=window
    )
