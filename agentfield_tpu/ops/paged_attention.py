"""Paged decode attention.

``paged_attention`` computes single-token GQA attention where K/V live in a
paged HBM pool indexed through per-sequence page tables (the kernel pattern
from the ragged-paged-attention line of work — see PAPERS.md).

Two implementations:

- ``ref``   — gather pages with XLA (materializes [B, max_ctx] K/V in HBM,
  correct everywhere incl. CPU tests; bandwidth-wasteful).
- ``pallas`` — Pallas TPU kernel that streams pages HBM→VMEM per sequence
  and never materializes the gathered context (added in ops/pallas; selected
  automatically on TPU backends once registered).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def paged_attention_ref(
    q: jax.Array,  # [B, H, hd]       — one query token per sequence
    k_pages: jax.Array,  # [P, Kh, ps, hd]  — one layer's page pool
    v_pages: jax.Array,  # [P, Kh, ps, hd]
    page_tables: jax.Array,  # [B, maxp] int32 page ids (0 = garbage page)
    seq_lens: jax.Array,  # [B] int32 — #valid tokens (incl. current) per sequence
    window: int | None = None,  # sliding window (Mistral): the query (at
    # position seq_len-1) attends keys within the most recent `window` only
) -> jax.Array:
    """Reference implementation via page gather. Returns [B, H, hd]."""
    B, H, hd = q.shape
    P, Kh, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    T = maxp * ps

    # [B, maxp, Kh, ps, hd] → [B, T, Kh, hd]
    k = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)
    v = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(B, T, Kh, hd)

    rep = H // Kh
    qg = q.reshape(B, Kh, rep, hd)
    logits = jnp.einsum("bkrh,btkh->bkrt", qg, k, preferred_element_type=jnp.float32)
    logits = logits * (hd ** -0.5)
    k_pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = k_pos < seq_lens[:, None]  # [B, T]
    if window is not None:
        valid = valid & (k_pos >= seq_lens[:, None] - window)
    logits = jnp.where(valid[:, None, None, :], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkrt,btkh->bkrh", probs, v, preferred_element_type=jnp.float32)
    return out.reshape(B, H, hd).astype(q.dtype)


def paged_attention(
    q, k_pages, v_pages, page_tables, seq_lens, impl: str = "ref", mesh=None,
    window: int | None = None,
):
    """Dispatch decode attention.

    With `mesh` (tensor parallelism), the Pallas kernel runs under shard_map
    over the KV-head axis: each shard owns its slice of the page pool
    ([P, Kh/tp, ps, hd] — KV pages shard on Kh, matching wk/wv's TP sharding)
    and computes its heads' attention with NO collectives — the psum over the
    output projection downstream is the only cross-chip traffic, exactly as
    in the ref GSPMD path. The `ref` impl needs no wrapper (XLA partitions
    the gather itself)."""
    if impl == "ref":
        return paged_attention_ref(
            q, k_pages, v_pages, page_tables, seq_lens, window=window
        )
    if impl == "pallas":
        from agentfield_tpu.ops.pallas.paged_attention_kernel import paged_attention_pallas

        # Mosaic kernels only compile for TPU; on CPU backends (tests, local
        # demos) run the same kernel in the Pallas interpreter.
        interpret = jax.default_backend() == "cpu"
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            from agentfield_tpu.parallel.mesh import AXIS_MODEL

            if mesh.shape.get(AXIS_MODEL, 1) > 1:
                import functools

                return shard_map(
                    functools.partial(
                        paged_attention_pallas, interpret=interpret, window=window
                    ),
                    mesh=mesh,
                    in_specs=(
                        P(None, AXIS_MODEL, None),  # q [B, H, hd] on heads
                        P(None, AXIS_MODEL, None, None),  # k_pages [P, Kh, ps, hd]
                        P(None, AXIS_MODEL, None, None),
                        P(None, None),  # page_tables replicated
                        P(None),  # seq_lens replicated
                    ),
                    out_specs=P(None, AXIS_MODEL, None),
                    check_rep=False,
                )(q, k_pages, v_pages, page_tables, seq_lens)
        return paged_attention_pallas(
            q, k_pages, v_pages, page_tables, seq_lens, interpret=interpret,
            window=window,
        )
    raise ValueError(f"unknown paged_attention impl {impl!r}")
