"""Continuous-batching inference engine.

This is the TPU-native heart of the framework: it replaces the reference's
external-LLM hot path (``Agent.ai()`` → litellm → provider API,
sdk/python/agentfield/agent_ai.py:95-447) with an in-tree engine, and its
scheduling semantics mirror the reference's async execution queue
(internal/handlers/execute.go:121-152,1302-1439): bounded admission with
explicit backpressure, and N concurrent requests coalesced into shared decode
steps (SURVEY §2.4 "serving engine" row; BASELINE.json configs[2]).

Design:

- **Decode** is one jitted step over a fixed ``max_batch`` of slots; inactive
  slots write their K/V to the reserved garbage page so shapes stay static.
- **Prefill** is one request at a time, padded to a static bucket length, KV
  scattered directly into the paged pool.
- **Host scheduler** (``step()``) admits pending requests when pages+slot are
  free (prefill-prioritized), otherwise runs a decode step; tokens stream out
  as ``TokenEvent``s — the transport layer (gRPC/SSE) subscribes to these the
  way reference clients subscribe to execution events
  (internal/handlers/execute.go:568).

All device work is functional: page pools are donated through the jitted
steps, so XLA updates them in place.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import math
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu import tracing
from agentfield_tpu.branching import branch_rid
from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models import llama
from agentfield_tpu.ops.kv_quant import write_pages as _write_pages
from agentfield_tpu.ops.paged_attention import ragged_paged_attention
from agentfield_tpu.serving.grammar import Grammar
from agentfield_tpu.serving.kv_cache import (
    PagedKVCache,
    PrefixPagePool,
    _kv_fault,
    build_page_table,
    pack_ragged_rows,
    page_chain_hashes,
)
from agentfield_tpu.serving.sampler import SamplingParams, sample_tokens

_MASKED = -1e30  # logit value for grammar-disallowed tokens
_MAX_STOP_IDS = 8  # per-request stop ids carried into the decode-step EOS mask


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 32  # concurrent decode slots
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 32  # max context = max_pages_per_seq * page_size
    max_pending: int = 1024  # admission queue bound (reference queue default:
    # AGENTFIELD_EXEC_ASYNC_QUEUE_CAPACITY=1024, execute.go:1373)
    attn_impl: str = "ref"  # decode-tick attention+KV-write: "ref" (XLA
    # scatter + gather) | "pallas" (the ONE ragged paged-attention kernel,
    # fused write — docs/KERNELS.md)
    kv_write_impl: str | None = None  # REMOVED (was a deprecated alias of
    # attn_impl after the ragged kernel fused the decode KV append into the
    # attention launch; its one-release window is over). Any value raises a
    # ValueError pointing at attn_impl="pallas" — docs/KERNELS.md.
    kv_quant_dtype: str = "none"  # quantized KV pages (docs/KERNELS.md
    # "Quantized pages"): "int8" | "fp8" store K/V pages in the quantized
    # dtype with per-(slot, kv-head) f32 scales — ~1.9x pages per HBM byte
    # and half the attention-phase page bandwidth; the ragged kernel
    # dequantizes inside its page-stream phase and the fused write
    # quantizes new K/V on the way in, so pages are never materialized in
    # bf16. The SAME representation ships through demote/restore, fork/COW
    # copies, and cross-node kv_fetch transfer, so the capacity win
    # compounds across tiers (docs/PREFIX_CACHING.md capacity math).
    # Greedy outputs can drift within the pinned kernel error bound;
    # rollback is "none" (the default) — bit-for-bit today's pools.
    prefill_impl: str = "ref"  # prefill attention: "ref" | "flash" (the
    # ragged kernel's dense-prefill packing — ops.pallas
    # dense_causal_attention; the standalone flash kernel is deleted) |
    # "ring" (sequence-parallel prefill over the mesh's `seq` axis — the
    # long-context serving path: no device materializes full-context
    # attention; requires mesh= with a seq axis, prompt buckets divide by
    # the axis size since they are powers of two >= 16)
    moe_prefill_impl: str = "dense"  # MoE FFN during PREFILL forwards:
    # "dense" soft-routes (exact, the default) | "sparse" capacity-based
    # top-k dispatch (FLOPs ∝ top_k not num_experts — prefill is
    # compute-bound, so big-MoE TTFT wants this; over-capacity tokens lose
    # that expert's contribution, cfg.moe_capacity_factor sizes headroom).
    # Decode always soft-routes: it is weight-bound (all expert weights
    # stream from HBM per step regardless) and dense-mix is exact.
    prefill_batch: int = 8  # admit up to this many fresh requests per tick as
    # ONE padded prefill batch (burst TTFT: N admissions cost one kernel call
    # instead of N serial prefills). 1 restores one-at-a-time admission.
    # Session-hit and chunked prefills still take the single-request path.
    # Tuning (measured, 32-req burst of 128-token prompts, llama-tiny):
    # on a serial backend (1-core CPU) 8 vs 4 cut burst TTFT p50 ~9% and
    # p99 ~24%; 32 flattened p99 to p50 but delays the FIRST requests'
    # tokens to the full-batch time — on TPU the batch dim rides the MXU
    # nearly free, so larger (16+) is better there, while latency-sensitive
    # single-request traffic is unaffected (admission batches only form
    # under backlog).
    admit_window: int = 8  # admission fairness: look up to this many requests
    # past a page-starved head each tick (FIFO head-of-line: a large request
    # waiting for pages must not starve smaller ones behind it — the
    # reference's async pool has no such hazard because its jobs don't hold
    # device memory, execute.go:1341). 1 restores strict FIFO.
    head_starve_fifo_ticks: int = 256  # anti-starvation for the head itself:
    # after this many consecutive ticks with the head page-starved while
    # later requests admit, the window collapses to 1 (strict FIFO) until
    # the head gets its pages — freed pages then flow to the head first.
    enable_prefix_cache: bool = True  # retain session KV across turns
    shared_prefix_cache: bool = True  # CROSS-REQUEST prefix reuse: prompt
    # pages are content-addressed (chained block hashes over full pages,
    # vLLM/SGLang-style) in a refcounted index, so any request — not just a
    # session's next turn — skips prefill for its longest cached full-page
    # prefix. Agent-fleet traffic shares system prompts/tool schemas, so the
    # burst-TTFT win dominates (ISSUE 1). Requires enable_prefix_cache;
    # False restores session-affinity-only reuse.
    prefill_chunk: int | None = None  # chunk long prefills to this many tokens:
    # bounds compiled bucket shapes and keeps decode latency fair under long
    # prompts (chunks run through the cached-page attention path). None
    # auto-resolves to 512 when chunk_attn_impl resolves to "pallas" (the
    # chunk kernel's VMEM budget caps at ~512 rows; without a default, long
    # prompts silently fell back to the O(T)-materializing gather) and to
    # no chunking otherwise.
    chunk_attn_impl: str = "auto"  # chunk-shaped launches (suffix/chunked
    # prefill, mixed ticks, speculative verify) through the ragged kernel:
    # "pallas" (pages stream HBM→VMEM, write fused) | "ref" (XLA scatter +
    # per-layer full-context page gather) | "auto" (pallas when the engine
    # already runs pallas anywhere: attn_impl=="pallas" or
    # prefill_impl=="flash"). Previously this was keyed on attn_impl alone,
    # which silently kept prefill_impl="flash", attn_impl="ref" configs on
    # the gather path.
    decode_buckets: tuple[int, ...] | None = None  # e.g. (4, 16): when fewer
    # slots are active, compact them into the smallest bucket width — the
    # unembed/attention cost scales with batch width, so low-occupancy decode
    # stops paying for max_batch (one extra compile per bucket)
    session_ttl: float = 600.0  # idle cached sessions release their pages
    # after this long even without allocation pressure (0 disables)
    host_cache_bytes: int = 0  # tiered KV (docs/PREFIX_CACHING.md "Tiered
    # cache"): byte budget of a host-RAM second tier under the shared-prefix
    # pool. Refcount-0 cached pages demote HBM→host (async device-to-host
    # copy on an offload worker, OFF the tick path) under allocation
    # pressure and when idle sessions expire; a prefix lookup or session
    # resume that matches a host-tier entry restores it into a freshly
    # allocated HBM page before admission — token-exact under greedy,
    # slower than an HBM hit, far cheaper than a re-prefill. Under HBM
    # pressure the engine thus degrades long-lived sessions to a slower
    # tier instead of silently losing them. 0 (the default) disables the
    # tier — the pool is bit-compatible with the single-tier behavior.
    # Requires shared_prefix_cache (the tier is content-addressed).
    prefix_sketch_bytes: int = 4096  # cluster tier (docs/PREFIX_CACHING.md
    # "Cluster tier"): byte cap on the prefix-index sketch published with
    # every heartbeat (truncated chain-hash digests, leading pages first) —
    # the gateway's prefix-affinity router scores dispatch candidates with
    # it. Overflow drops the deepest records and counts
    # prefix_sketch_truncated_total. 0 disables publication (the node never
    # attracts affinity traffic; routing degrades to load order).
    # $AGENTFIELD_PREFIX_SKETCH_BYTES overrides the default at node build.
    grammar_slots: int = 0  # constrained-decoding state capacity (rows of the
    # device-resident token-transition bank). 0 disables the masking path —
    # the decode step then skips the [B, V] mask gather entirely. Each
    # submitted Request.grammar occupies grammar.n_states rows (shared across
    # requests carrying the same Grammar object).
    decode_span: int = 1  # decode steps per dispatch: the jitted decode runs
    # a lax.scan of this many steps and returns [span, B] tokens, so the host
    # pays ONE device→host readback per span tokens instead of per token.
    # Sized for high-latency links (the axon tunnel's readback is ~100ms —
    # round-1 bench's 210ms/step was mostly this): span 8-16 amortizes it to
    # noise. Finished rows keep decoding to the end of their span (their
    # extra tokens are discarded at harvest; stale writes land on pages the
    # host hasn't freed yet or on the garbage page) — the waste is bounded by
    # span-1 steps per finished request. 1 restores per-token dispatch.
    async_decode: bool = True  # pipeline decode: dispatch step N before
    # reading step N-1's sampled tokens, so the device never idles on the
    # host's device→host round trip (token events arrive one tick later;
    # greedy streams are bit-identical either way). False restores the
    # dispatch-and-wait scheduler.
    mixed_step: bool | str = False  # token-budget MIXED scheduling
    # (docs/MIXED_SCHEDULING.md): when prompts arrive while decodes are in
    # flight, one jitted ragged forward per tick packs ONE decode token per
    # active slot plus up to (mixed_step_budget - n_active) prefill-chunk
    # tokens from admitting requests — chunked prefill piggybacks on decode
    # (Sarathi-style), so prompt bursts stop freezing in-flight decodes and
    # long prompts stop delaying admission. Worst-case inter-token latency is
    # bounded by the budget, not the longest prompt. "auto" enables it when
    # speculative decoding is off (spec decode owns its ticks); False
    # preserves the classic prefill-XOR-decode tick bit-for-bit. Mixed ticks
    # pause (classic ticks resume) while any grammar-constrained request is
    # active — the decode-step grammar mask is a classic-tick feature.
    mixed_step_budget: int = 512  # tokens per mixed tick (decode rows +
    # prefill-chunk rows, padded to this static shape — ONE compile per
    # budget instead of a prefill-bucket x decode-bucket matrix). Must be
    # >= max_batch + 16 so a full decode batch still leaves chunk room.
    compile_cache_dir: str | None = None  # persistent JAX compilation cache
    # (jax_compilation_cache_dir): warm restarts skip the multi-second
    # compile gate. None falls back to $AGENTFIELD_COMPILE_CACHE; empty/unset
    # leaves the cache off. Logged (entries found = warm) at engine startup.
    preempt_fence_ticks: int = 64  # overload control (docs/FAULT_TOLERANCE.md):
    # when a pending request of HIGHER priority than some active slot has
    # been page/slot-starved for this many consecutive ticks, the scheduler
    # preempts the lowest-priority active slot — its KV pages are parked in
    # the shared-prefix index (refcount-0 cached, nothing recomputed unless
    # evicted) and the request re-queues with its generated-so-far suffix
    # appended to the prompt, so resume re-admits through the normal
    # shared-prefix path and continues token-exactly under greedy. 0
    # disables priority preemption (the engine.preempt_storm fault point
    # still forces preemptions for chaos testing). Requires
    # shared_prefix_cache for cheap resume; with the cache off a preempted
    # request re-prefills its full context on resume (still correct).
    spec_k: int = 0  # speculative decoding: draft proposals per step (0
    # disables). Requires a draft model (InferenceEngine(draft=...)). Each
    # eligible step a small draft model proposes spec_k greedy tokens and the
    # target verifies them in ONE (spec_k+1)-wide forward — accepted-prefix +
    # correction emits 1..spec_k+1 tokens per target pass (classic
    # draft-verify; exact greedy equivalence). Eligibility is per dispatch:
    # every active row greedy (temperature 0) and unconstrained; mixed
    # batches fall back to normal decode for that step.
    spec_prefill: bool = True  # agent-aware serving (docs/OPERATIONS.md
    # "Agent-aware serving"): session keep-warm pins + speculative next-step
    # prefill for requests that arrive with expect_followup. DISTINCT from
    # spec_k (speculative DECODING above): this speculates the next
    # REQUEST's prefill, not the current request's tokens. Only
    # expect_followup traffic takes any new path — default traffic is
    # untouched either way — and False (env AGENTFIELD_SPEC_PREFILL=0 at
    # node build) gates every pin/speculation code path off, bit-compatible
    # with the pre-agent-aware scheduler (pinned by test).
    spec_pin_ttl: float = 120.0  # seconds a keep-warm session pin survives
    # without its follow-up arriving. An expired pin releases its
    # speculative pages and the session falls back to the ordinary
    # session_ttl clock — a tool call that never returns cannot hold HBM
    # forever.
    spec_pin_budget: int = 32  # max concurrently pinned sessions. Pinning
    # past the budget spills the OLDEST pin (LRU), and the allocation
    # pressure ladder (_alloc_with_eviction) spills pins before failing —
    # pins can never starve admission.
    spec_max_candidates: int = 4  # cap on declared candidate tool outcomes
    # speculatively prefilled per step (the COW fan-out bound: each
    # candidate is one engine-internal prefill job + its suffix pages).
    dtype: str | None = None

    @property
    def max_context(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def prefill_bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_context)

    def mixed_bucket(self, n: int) -> int:
        """Padded width of a mixed tick carrying n real tokens: powers of two
        from 16, capped at the budget (a lightly loaded tick — few decodes, a
        short chunk tail — pays a small forward, not the full budget)."""
        b = 16
        while b < min(n, self.mixed_step_budget):
            b *= 2
        return min(b, self.mixed_step_budget)


# Live-slot handoff stash bounds (disaggregated pools): how long a phase-1
# export waits for the decode node's tail fetch (and an adopted tail waits
# for its phase-2 admission) before aging out, and how many entries either
# stash may hold. Each entry pins one host page copy (~page bytes), so the
# cap bounds handoff memory at ~64 pages even under a stuck decode pool.
_HANDOFF_TTL_S = 60.0
_HANDOFF_STASH_MAX = 64

# Admission priority of engine-internal speculative prefill jobs: the bottom
# of every tier order, so speculation only ever consumes idle budget — any
# caller-submitted request (even priority -1 traffic) admits first, and the
# preemption probe picks spec slots as its first victims.
_SPEC_PRIORITY = -(1 << 30)


@dataclasses.dataclass
class Request:
    id: str
    prompt: list[int]
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    # Session affinity for prefix-cache reuse (north-star config 4: agent→
    # agent call chains share KV). Conversations grow monotonically, so a
    # session's cached tokens are always a prefix of the next prompt.
    session_id: str | None = None
    # Constrained decoding: schema-invalid tokens are masked before sampling
    # (serving/grammar.py). Requires sampling.stop_token_ids — EOS is the only
    # way a completed value can terminate. Replaces the reference's prompt-
    # injection + regex-salvage structured output (agent_ai.py:221-245,424-447).
    grammar: Grammar | None = None
    # Multimodal early fusion: [(offset, embeds [k, hidden_size])] — the
    # embeddings replace the prompt's placeholder tokens at those positions
    # during prefill (vision tower output, models/vision.py). MM requests skip
    # session prefix caching: cache identity keys on token ids, which cannot
    # distinguish two images behind identical placeholders.
    mm_embeds: list[tuple[int, Any]] | None = None
    # Wall-clock budget in SECONDS from submit. When it expires the request
    # is cancelled through the request_cancel path and a final TokenEvent
    # with finish_reason="deadline_exceeded" is emitted (tokens generated so
    # far were already streamed). Enforced for PENDING work too: a request
    # that expires before it ever admits is shed from the queue with the
    # same terminal event (stats["shed_pending_deadline_total"]). None = no
    # deadline; enforcement costs one empty-dict check per step when unused
    # (docs/FAULT_TOLERANCE.md).
    deadline_s: float | None = None
    # Admission priority (overload control, docs/FAULT_TOLERANCE.md):
    # HIGHER values admit first — the pending queue is kept priority-tier-
    # ordered at submit (FIFO within a tier, so all-default traffic is
    # bit-identical to the pre-priority scheduler). A higher-priority
    # request page/slot-starved past EngineConfig.preempt_fence_ticks
    # preempts the lowest-priority active slot. Under sustained high-tier
    # load lower tiers wait indefinitely — strict priority is the point;
    # give droppable traffic a deadline_s so the pending sweep sheds it.
    # The head_starve_fifo_ticks anti-starvation fence still collapses the
    # admit window to strict FIFO when admissions keep bypassing the (top-
    # tier, oldest) queue head.
    priority: int = 0
    # Tokens generated by a PREVIOUS incarnation of this request (set by the
    # engine when it preempts a slot and re-queues the request with its
    # generated-so-far suffix folded into the prompt). TokenEvent.index
    # continues from here so stream consumers see one uninterrupted
    # sequence; sampling.max_new_tokens was already decremented by the same
    # amount. 0 for every caller-submitted request.
    resumed_from: int = 0
    # Branch decoding (test-time scaling, docs/PREFIX_CACHING.md "Fork / COW
    # branches"): when > 1, the request FORKS into this many sibling
    # branches the moment its prefill completes — siblings share the
    # prompt's full KV pages copy-on-write (incref, no re-prefill, no H2D),
    # only the partial tail page is copied, and each branch samples its
    # first token from the same last-prompt-token logits under its own RNG
    # stream. Siblings decode as ordinary batch-mates (ids
    # ``branching.branch_rid(id, j)``; branch 0 keeps this id and is
    # token-exact vs the unforked request under greedy). Pruning/scoring
    # lives OUTSIDE the engine (branching.BranchGroup drives request_cancel
    # / request_fork). Exclusive with grammar/mm_embeds; sibling clones
    # drop session_id (N branches must not fight over one session entry).
    n_branches: int = 1
    # Request-scoped tracing (docs/OBSERVABILITY.md): the TraceContext dict
    # minted by the gateway ({"trace_id", "attempt", "node"}), threaded
    # through the generate input. When present, the engine records lifecycle
    # spans (queue-wait, prefill, decode, park/resume, kv-restore, fork)
    # against the trace id in the process tracer buffer; the node ships them
    # back on the terminal frame. None (the default, and anything that fails
    # tracing.valid_context) records nothing — the untraced hot path costs
    # one dict miss per event.
    trace: Any = None
    # Disaggregated prefill/decode pools (docs/ARCHITECTURE.md "Two-phase
    # dispatch"). handoff_export=True: this node is PHASE ONE — prefill,
    # sample the first token, publish the prompt's full pages into the
    # prefix index, stash the partial tail page + sampler state for export,
    # and emit ONE terminal event (finish_reason="handoff") instead of
    # decoding. Ineligible requests (grammar/mm/branched, too-short prompt,
    # first token already terminal, shared-prefix cache off) silently fall
    # through to ordinary single-node prefill+decode — the degradation
    # contract every failure mode shares.
    handoff_export: bool = False
    # PHASE TWO marker: the descriptor the phase-1 node returned
    # ({"id", "t0", "logprob", "prompt_tokens", "pages", "page_size"}).
    # When the adopted tail payload for desc["id"] is present and the prefix
    # walk matched every full prompt page, admission installs the slot LIVE
    # (zero prefill, first token = t0); otherwise the request admits
    # normally and greedy re-samples the same t0 — token-exact fallback.
    handoff: dict | None = None
    # Agent-aware serving (docs/OPERATIONS.md "Agent-aware serving"): the
    # caller expects a fast follow-up on this session (a tool call is about
    # to run and its result comes straight back). On finish the engine PINS
    # the session — its KV stays warm instead of racing session_ttl/LRU
    # eviction until the follow-up admits or the pin expires
    # (EngineConfig.spec_pin_ttl). No-op with spec_prefill off or without a
    # session_id.
    expect_followup: bool = False
    # Declared candidate tool outcomes (token sequences): with
    # expect_followup, each candidate is speculatively prefilled as a
    # zero-priority engine-internal job over the session's cached prefix in
    # idle budget; when the real follow-up arrives the prefix index absorbs
    # the winner (TTFT pays only the unspeculated suffix) and the losers'
    # pages are freed immediately. Capped at
    # EngineConfig.spec_max_candidates.
    followup_candidates: list[list[int]] | None = None
    # INTERNAL marker: this request IS a speculative prefill job the engine
    # spawned on behalf of parent request id ``spec_parent``. Such jobs are
    # disposable — max_new_tokens=1, bottom-priority, their pages stash
    # into the parent session's speculation state at release instead of
    # freeing, and no caller ever holds a future/stream for them. Never set
    # by callers.
    spec_parent: str | None = None


@dataclasses.dataclass
class TokenEvent:
    request_id: str
    token: int
    index: int  # 0-based index among generated tokens
    finished: bool
    finish_reason: str | None = None  # "stop" | "length" |
    # "deadline_exceeded" (Request.deadline_s expired; token is -1)
    logprob: float | None = None  # log P(token) under the UNMODIFIED (pre-
    # temperature/top-k/top-p) distribution — raw-logit log-softmax


@dataclasses.dataclass
class _Slot:
    req: Request
    pages: list[int]
    length: int  # tokens whose K/V are (or will be) cached, incl. pending last token
    generated: int
    last_token: int
    tokens: list[int] = dataclasses.field(default_factory=list)  # full history
    # (prompt + generated) — retained for session prefix caching
    draft_len: int = 0  # speculative decoding: the length through which the
    # DRAFT cache is synced (normal-decode fallback steps advance the target
    # only; before the next spec step the gap replays through the draft —
    # without this, a single sampled request joining the batch would
    # permanently collapse the acceptance rate)
    last_emit_t: float = 0.0  # wall time of this slot's last emitted token
    # (perf_counter): feeds the engine's inter-token-latency window


@dataclasses.dataclass
class _SessionEntry:
    pages: list[int]
    tokens: list[int]  # tokens whose KV is resident (prompt + generated[:-1])
    last_used: float


@dataclasses.dataclass
class _PrefillJob:
    """An admitting request whose prompt prefills CHUNK BY CHUNK across mixed
    ticks (docs/MIXED_SCHEDULING.md). The job owns its pages (acquired with
    the same session/shared-prefix machinery as classic admission — the
    cached-prefix hoist decides ``start``) and reserves one decode slot by
    count (``_slots_available``); it installs into a concrete slot only when
    the final prompt token's logits come back."""

    req: Request
    pages: list[int]
    row: Any  # np.ndarray page-table row [max_pages_per_seq]
    start: int  # cached-prefix length: prefill begins here
    pos: int  # next absolute position to prefill (== start at creation)
    lead_hash: bytes | None = None  # chain hash of the prompt's first full
    # page: pending requests sharing it defer until this job publishes at
    # install, instead of redundantly re-prefilling the same prefix


def _sparse_prefill_cfg(cfg: LlamaConfig, ecfg: "EngineConfig") -> LlamaConfig:
    """The cfg a PREFILL forward runs under: flipped to sparse-dispatch MoE
    when the knob asks for it (one constructor for target and draft, so the
    two cannot drift)."""
    if ecfg.moe_prefill_impl == "sparse" and cfg.num_experts > 0:
        return dataclasses.replace(cfg, moe_impl="sparse")
    return cfg


def _decode_impl(ecfg: EngineConfig) -> str:
    """Impl for decode-tick ragged launches (the fused kernel replaced both
    the old decode-attention kernel and the kv-write patch kernel)."""
    return "pallas" if ecfg.attn_impl == "pallas" else "ref"


def _binding_window(cfg: LlamaConfig, ecfg: EngineConfig) -> int | None:
    """The sliding window, or None when it cannot bind within this engine's
    context budget (kernels stay usable for short-context serving of
    windowed models like Mistral)."""
    w = cfg.sliding_window
    if w is None or w >= ecfg.max_context:
        return None
    return w


@functools.lru_cache(maxsize=None)
def _decode_fn(cfg: LlamaConfig, ecfg: EngineConfig, mesh=None):
    """Jitted decode dispatch, cached per (model, engine, mesh) config so
    every engine instance shares one compilation. Runs ``ecfg.decode_span``
    steps as one on-device scan; returns [span, B] tokens/logprobs."""

    def one_step(
        params, k_pages, v_pages, tokens, seq_lens, page_tables, rng, temps,
        top_ks, top_ps, gstates, trans_bank, accept_bank, eos_ids,
    ):
        B = tokens.shape[0]
        positions = seq_lens  # 0-based position of the incoming token
        x = llama.embed_tokens(params, cfg, tokens)[:, None, :]  # [B,1,D]
        cos, sin = llama.rope_sincos(positions[:, None], cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        # Decode is B one-token ragged rows: row b's cached context is its
        # seq_len keys, its single new token sits AT seq_len. The ragged
        # kernel fuses the KV write (over-budget speculative steps route to
        # the garbage page inside it) and the attention over cache + self.
        n_toks = (seq_lens > 0).astype(seq_lens.dtype)
        row_ids = jnp.arange(B, dtype=jnp.int32)

        def body(x, xs):
            lp, kp, vp = xs
            h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, k, v = llama.qkv_proj(lp, h, cfg, cos, sin)  # [B, 1, ...]
            attn, kp, vp = ragged_paged_attention(
                q, k, v, kp, vp, page_tables, seq_lens, n_toks, seq_lens,
                row_ids, impl=_decode_impl(ecfg), mesh=mesh,
                window=_binding_window(cfg, ecfg),
            )
            x = x + (attn.reshape(B, 1, -1) @ lp["wo"]).astype(x.dtype)
            x = x + llama.mlp_block(lp, x, cfg)
            return x, (kp, vp)

        x, (kp, vp) = jax.lax.scan(body, x, (params["layers"], k_pages, v_pages))
        logits = llama.unembed(params, cfg, x)[:, 0]  # [B, V]
        if ecfg.grammar_slots > 0:
            B_, V = logits.shape

            def constrained(_):
                # Constrained decoding: one [B, V] row gather from the
                # transition bank masks schema-invalid tokens; the request's
                # stop ids are additionally allowed in accepting states. Free
                # rows sit in bank row 0 (all-zero: every token allowed,
                # state stays 0), so they ride the same step.
                rows = jnp.take(trans_bank, gstates, axis=0).astype(jnp.int32)
                allowed = rows >= 0
                stop_allow = jnp.zeros((B_, V), jnp.bool_).at[
                    jnp.arange(B_)[:, None], jnp.clip(eos_ids, 0, V - 1)
                ].max(eos_ids >= 0)
                allowed = allowed | (stop_allow & accept_bank[gstates][:, None])
                toks = sample_tokens(
                    jnp.where(allowed, logits, _MASKED), rng, temps, top_ks, top_ps
                )
                new_g = jnp.maximum(
                    jnp.take_along_axis(rows, toks[:, None], axis=1)[:, 0], 0
                )
                return toks, new_g

            def free(_):
                return sample_tokens(logits, rng, temps, top_ks, top_ps), gstates

            # Unconstrained steps skip the bank gather entirely at runtime.
            next_tokens, new_gstates = jax.lax.cond(
                jnp.any(gstates > 0), constrained, free, None
            )
        else:
            next_tokens = sample_tokens(logits, rng, temps, top_ks, top_ps)
            new_gstates = gstates
        logprobs = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), next_tokens[:, None], axis=-1
        )[:, 0]
        # Advance lengths on-device (active slots have seq_len > 0) so the
        # host never re-uploads control state during steady-state decode.
        new_seq_lens = seq_lens + (seq_lens > 0).astype(seq_lens.dtype)
        return next_tokens, logprobs, new_seq_lens, new_gstates, kp, vp

    span = max(1, ecfg.decode_span)

    def decode(
        params, k_pages, v_pages, tokens, seq_lens, page_tables, rng, temps,
        top_ks, top_ps, gstates, trans_bank, accept_bank, eos_ids,
    ):
        def body(carry, step_rng):
            toks, lens, gs, kp, vp = carry
            nt, lp, lens, gs, kp, vp = one_step(
                params, kp, vp, toks, lens, page_tables, step_rng, temps,
                top_ks, top_ps, gs, trans_bank, accept_bank, eos_ids,
            )
            return (nt, lens, gs, kp, vp), (nt, lp)

        (tokens, seq_lens, gstates, kp, vp), (toks, lps) = jax.lax.scan(
            body,
            (tokens, seq_lens, gstates, k_pages, v_pages),
            jax.random.split(rng, span),
        )
        # toks/lps: [span, B]; tokens (= toks[-1]) seeds the next dispatch.
        return toks, lps, seq_lens, gstates, tokens, kp, vp

    return jax.jit(decode, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _spec_decode_fn(cfg: LlamaConfig, dcfg: LlamaConfig, ecfg: EngineConfig, mesh=None):
    """Jitted speculative decode step with PER-ROW verification modes: the
    DRAFT model proposes ``spec_k`` tokens autoregressively, the TARGET
    verifies them in one (spec_k+1)-wide batched chunk forward over the
    paged cache, and each row emits its accepted prefix plus a correction
    token — 1..spec_k+1 tokens per target pass.

    Row modes (mixed freely in one dispatch):

    - greedy (temperature<=0): accept while draft == target argmax;
      correction is the target argmax — bit-identical to plain greedy.
    - plain-temperature (top_k=0, top_p>=1): textbook rejection sampling
      (Leviathan et al.): accept d with prob min(1, p(d)/q(d)) where p/q are
      the TEMPERED target/draft distributions; on rejection sample the
      normalized residual max(p-q, 0); on full acceptance sample p directly.
      The emitted distribution is exactly the plain sampler's.
    - truncated (top_k>0 or top_p<1): proposals are auto-rejected and the
      correction samples the exact truncated distribution via sample_tokens
      on the first verify position — 1 token per dispatch, same progress and
      distribution as normal decode (truncation-aware acceptance would need
      the filtered q/p vectors; not worth the complexity for these rows).

    Grammar-constrained rows still exclude the whole dispatch (engine
    ``_spec_eligible``): draft proposals are unsampleable mid-schema.

    Both models share the page TABLES and lengths; the draft keeps its own
    page pool (same page ids — one allocator governs both). The draft runs
    spec_k+1 steps so its cache also holds the last proposal's KV when
    everything is accepted."""
    k = ecfg.spec_k
    W = k + 1  # verify width

    def draft_step(dparams, kp, vp, tokens, seq_lens, page_tables, temps, rng):
        """One draft step: greedy rows take the argmax, sampled rows draw
        from the TEMPERED draft distribution (whose probabilities the
        verifier needs for the acceptance ratio — returned as ``q``)."""
        B = tokens.shape[0]
        x = llama.embed_tokens(dparams, dcfg, tokens)[:, None, :]
        cos, sin = llama.rope_sincos(
            seq_lens[:, None], dcfg.head_dim, dcfg.rope_theta, dcfg.rope_scaling
        )
        n_toks = (seq_lens > 0).astype(seq_lens.dtype)
        row_ids = jnp.arange(B, dtype=jnp.int32)

        def body(x, xs):
            lp, kp, vp = xs
            h = llama.rms_norm(x, lp["attn_norm"], dcfg.rms_norm_eps)
            q, kk, vv = llama.qkv_proj(lp, h, dcfg, cos, sin)
            attn, kp, vp = ragged_paged_attention(
                q, kk, vv, kp, vp, page_tables, seq_lens, n_toks, seq_lens,
                row_ids, impl=_decode_impl(ecfg), mesh=mesh,
                window=_binding_window(dcfg, ecfg),
            )
            x = x + (attn.reshape(B, 1, -1) @ lp["wo"]).astype(x.dtype)
            x = x + llama.mlp_block(lp, x, dcfg)
            return x, (kp, vp)

        x, (kp, vp) = jax.lax.scan(body, x, (dparams["layers"], kp, vp))
        logits = llama.unembed(dparams, dcfg, x)[:, 0]
        t = jnp.maximum(temps, 1e-6)[:, None]
        q = jax.nn.softmax(logits / t, axis=-1)  # [B, V] tempered draft dist
        sampled = jax.random.categorical(rng, logits / t, axis=-1).astype(jnp.int32)
        nt = jnp.where(temps <= 0, jnp.argmax(logits, axis=-1).astype(jnp.int32), sampled)
        new_lens = seq_lens + (seq_lens > 0).astype(seq_lens.dtype)
        return nt, q, new_lens, kp, vp

    def verify(params, k_pages, v_pages, x_tokens, seq_lens, page_tables):
        """Target forward over W positions per row (batched ragged chunk:
        every row at its own start position), writing KV for all W and
        returning [B, W, V] logits."""
        B = x_tokens.shape[0]
        active = seq_lens > 0
        positions = seq_lens[:, None] + jnp.arange(W, dtype=seq_lens.dtype)  # [B, W]
        x = llama.embed_tokens(params, cfg, x_tokens)  # [B, W, D]
        cos, sin = llama.rope_sincos(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        # One W-token ragged row per sequence: cached context = seq_len keys,
        # the W verify tokens are the launch's new keys (write fused).
        n_toks = jnp.where(active, W, 0).astype(seq_lens.dtype)
        row_ids = jnp.arange(B, dtype=jnp.int32)

        def body(x, xs):
            lp, kp, vp = xs
            h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, kk, vv = llama.qkv_proj(lp, h, cfg, cos, sin)
            attn, kp, vp = ragged_paged_attention(
                q, kk, vv, kp, vp, page_tables, seq_lens, n_toks, seq_lens,
                row_ids, impl=ecfg.chunk_attn_impl, mesh=mesh,
                window=_binding_window(cfg, ecfg),
            )
            x = x + (attn.reshape(B, W, -1) @ lp["wo"]).astype(x.dtype)
            x = x + llama.mlp_block(lp, x, cfg)
            return x, (kp, vp)

        x, (kp, vp) = jax.lax.scan(body, x, (params["layers"], k_pages, v_pages))
        return llama.unembed(params, cfg, x), kp, vp  # [B, W, V]

    def spec(
        params, k_pages, v_pages, dparams, dk_pages, dv_pages,
        tokens, seq_lens, page_tables, temps, top_ks, top_ps, rng,
    ):
        B = tokens.shape[0]
        active = seq_lens > 0
        step_keys = jax.random.split(rng, k + 4)  # k+1 draft steps + 3 own
        accept_key, resid_key, corr_key = (
            step_keys[k + 1], step_keys[k + 2], step_keys[k + 3]
        )

        def dbody(carry, step_key):
            toks, lens, kp, vp = carry
            nt, q, lens, kp, vp = draft_step(
                dparams, kp, vp, toks, lens, page_tables, temps, step_key
            )
            return (nt, lens, kp, vp), (nt, q)

        # k+1 draft steps: proposals d_1..d_k plus one extra step that writes
        # d_k's KV into the draft cache (needed when all k are accepted).
        (_, _, dk_pages, dv_pages), (drafts, qstack) = jax.lax.scan(
            dbody, (tokens, seq_lens, dk_pages, dv_pages), step_keys[:k + 1]
        )
        dmat = jnp.swapaxes(drafts[:k], 0, 1)  # [B, k] = d_1..d_k
        qs = jnp.swapaxes(qstack[:k], 0, 1)  # [B, k, V] draft dists
        x_tokens = jnp.concatenate([tokens[:, None], dmat], axis=1)  # [B, W]
        logits, k_pages, v_pages = verify(
            params, k_pages, v_pages, x_tokens, seq_lens, page_tables
        )
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, W]

        greedy_row = temps <= 0
        truncated_row = (top_ks > 0) | (top_ps < 1.0)
        t = jnp.maximum(temps, 1e-6)[:, None, None]
        p = jax.nn.softmax(logits / t, axis=-1)  # [B, W, V] tempered target
        # Acceptance per mode. Greedy: exact argmax agreement. Sampled:
        # u < p(d)/q(d) (as u*q < p — robust at q→0). Truncated: never.
        match_greedy = dmat == g[:, :k]
        p_d = jnp.take_along_axis(p[:, :k], dmat[..., None], axis=2)[..., 0]  # [B, k]
        q_d = jnp.take_along_axis(qs, dmat[..., None], axis=2)[..., 0]  # [B, k]
        u = jax.random.uniform(accept_key, (B, k))
        match_sampled = u * q_d < p_d
        match = jnp.where(
            greedy_row[:, None],
            match_greedy,
            jnp.where(truncated_row[:, None], False, match_sampled),
        )
        m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)  # [B] 0..k
        # Correction token at position m, per mode. Residual sampling needs
        # q at the rejection position; position k (full acceptance) has no
        # draft — its "q" is zero so the residual IS p (plain p-sample).
        p_m = jnp.take_along_axis(p, m[:, None, None], axis=1)[:, 0]  # [B, V]
        qs_pad = jnp.concatenate([qs, jnp.zeros((B, 1, qs.shape[-1]), qs.dtype)], axis=1)
        q_m = jnp.take_along_axis(qs_pad, m[:, None, None], axis=1)[:, 0]  # [B, V]
        residual = jnp.maximum(p_m - q_m, 0.0)
        rsum = jnp.sum(residual, axis=-1, keepdims=True)
        # p==q numerics can zero the residual; fall back to p itself then.
        resid_dist = jnp.where(rsum > 1e-9, residual, p_m)
        resid_tok = jax.random.categorical(
            resid_key, jnp.where(resid_dist > 0, jnp.log(jnp.maximum(resid_dist, 1e-30)), -jnp.inf),
            axis=-1,
        ).astype(jnp.int32)
        # Greedy rows: sample_tokens == argmax (bit-exact). Truncated rows
        # (m=0): the exact truncated sampler over the normal-decode logits.
        l_m = jnp.take_along_axis(logits, m[:, None, None], axis=1)[:, 0]  # [B, V]
        exact_corr = sample_tokens(l_m, corr_key, temps, top_ks, top_ps)
        plain_sampled = (~greedy_row) & (~truncated_row)
        c = jnp.where(plain_sampled, resid_tok, exact_corr)[:, None]  # [B, 1]
        t_idx = jnp.arange(W, dtype=jnp.int32)[None]  # [1, W]
        dmat_pad = jnp.concatenate([dmat, jnp.zeros((B, 1), jnp.int32)], axis=1)
        emitted = jnp.where(t_idx < m[:, None], dmat_pad, c)  # [B, W]
        lsm = jax.nn.log_softmax(logits, axis=-1)
        lps = jnp.take_along_axis(lsm, emitted[:, :, None], axis=2)[:, :, 0]
        counts = jnp.where(active, m + 1, 0)
        new_seq_lens = seq_lens + counts.astype(seq_lens.dtype)
        next_tokens = jnp.where(active, c[:, 0], tokens)
        return (
            jnp.swapaxes(emitted, 0, 1),  # [W, B] harvest shape
            jnp.swapaxes(lps, 0, 1),
            counts,
            new_seq_lens,
            next_tokens,
            k_pages, v_pages, dk_pages, dv_pages,
        )

    return jax.jit(spec, donate_argnums=(1, 2, 4, 5))


@functools.lru_cache(maxsize=None)
def _copy_page_fn():
    """Jitted device-side page copy (copy-on-write): duplicate one page's
    K/V across all layers into a fresh page. Pools are pytrees (plain
    arrays, or QuantPages values+scales under kv_quant_dtype — a COW copy
    moves the quantized bytes AND their scales, so a forked tail is
    bit-identical to its parent); jit re-specializes per pool structure,
    so the target and draft caches share this builder."""

    def cp(kp, vp, src, dst):
        cp1 = lambda a: a.at[:, dst].set(a[:, src])  # noqa: E731
        return jax.tree.map(cp1, kp), jax.tree.map(cp1, vp)

    return jax.jit(cp, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def _restore_page_fn():
    """Jitted host→device page restore (tiered KV, docs/PREFIX_CACHING.md
    "Tiered cache"): write a BATCH of pages' K/V across all layers back
    into the paged pool in one dispatch (``dst`` is [N]; value leaves [L,
    N, ...]) — one lookup's worth of restores costs one call, not one per
    page. Quantized pools restore values + scales leaf-by-leaf (the
    round-tripped bytes are bit-identical either way). jit re-specializes
    per (pool structure, N) like _copy_page_fn."""

    def up(kp, vp, k, v, dst):
        def up1(pool, host):
            return pool.at[:, dst].set(host.astype(pool.dtype))

        return jax.tree.map(up1, kp, k), jax.tree.map(up1, vp, v)

    return jax.jit(up, donate_argnums=(0, 1))


def _fetch_page_kv(handle):
    """Offload-worker side of a KV demote: the blocking device→host
    transfer of one captured page (runs on the pool's offload thread, no
    locks held — see InferenceEngine._capture_page_kv for why the handle's
    content is immune to the scheduler's concurrent donating dispatches).
    The handle is a (k, v) pair of per-page pytrees (plain slices, or
    QuantPages values+scales); every leaf lands as numpy."""
    return jax.tree.map(np.asarray, handle)


@functools.lru_cache(maxsize=None)
def _prefill_fn(cfg: LlamaConfig, ecfg: EngineConfig, bucket: int, mesh=None):
    ps = ecfg.page_size

    def prefill(params, k_pages, v_pages, tokens, length, page_table_row):
        # tokens: [1, bucket]; positions past `length` are padding whose
        # K/V are routed to the garbage page.
        positions = jnp.arange(bucket, dtype=jnp.int32)[None]
        pos = positions[0]
        in_range = pos < length
        logits, (ks, vs) = llama.forward_impl(
            params, cfg, tokens, positions, attn_impl=ecfg.prefill_impl, mesh=mesh,
            valid_mask=in_range[None],
        )
        page_ids = jnp.where(in_range, page_table_row[pos // ps], 0)
        slot_ids = pos % ps
        # pages: [L, P, Kh, ps, hd]; advanced indices at dims 1,3 put the
        # token dim first → value layout [bucket, L, Kh, hd]. write_pages
        # quantizes per slot when the pool is QuantPages (kv_quant_dtype).
        k_pages = _write_pages(k_pages, jnp.swapaxes(ks[:, 0], 0, 1), page_ids, slot_ids)
        v_pages = _write_pages(v_pages, jnp.swapaxes(vs[:, 0], 0, 1), page_ids, slot_ids)
        last = logits[0, length - 1]
        return last, k_pages, v_pages

    return jax.jit(prefill, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _batch_prefill_fn(cfg: LlamaConfig, ecfg: EngineConfig, bucket: int, mesh=None):
    """Prefill up to ``ecfg.prefill_batch`` fresh prompts in ONE forward pass
    (rows are independent batch entries; per-row K/V scatter into each row's
    own pages). Rows past the live count have length 0: every write routes to
    the garbage page and their logits are ignored. One compilation per bucket
    (the row count is static), so a 256-request burst costs ceil(256/N)
    kernel calls instead of 256 serial prefills."""
    ps = ecfg.page_size
    N = ecfg.prefill_batch

    def prefill(params, k_pages, v_pages, tokens, lengths, rows):
        # tokens [N, bucket]; lengths [N]; rows [N, max_pages_per_seq]
        positions = jnp.arange(bucket, dtype=jnp.int32)[None].repeat(N, 0)
        in_range = positions < lengths[:, None]
        logits, (ks, vs) = llama.forward_impl(
            params, cfg, tokens, positions, attn_impl=ecfg.prefill_impl, mesh=mesh,
            valid_mask=in_range,
        )
        page_ids = jnp.where(
            in_range, jnp.take_along_axis(rows, positions // ps, axis=1), 0
        )  # [N, bucket]
        slot_ids = positions % ps
        # ks/vs: [L, N, bucket, Kh, hd] → rows scatter into disjoint pages
        # (padding rows all hit garbage page 0; last-write-wins there is fine).
        # Advanced [N, bucket] indices at dims 1,3 of [L, P, Kh, ps, hd] put
        # the broadcast dims first → value layout [N, bucket, L, Kh, hd].
        k_pages = _write_pages(k_pages, jnp.moveaxis(ks, 0, 2), page_ids, slot_ids)
        v_pages = _write_pages(v_pages, jnp.moveaxis(vs, 0, 2), page_ids, slot_ids)
        last = jnp.take_along_axis(
            logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0]  # [N, V]
        return last, k_pages, v_pages

    return jax.jit(prefill, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _prefill_inject_fn(cfg: LlamaConfig, ecfg: EngineConfig, bucket: int, mesh=None):
    """Whole-prompt prefill with embedding injection (multimodal): like
    ``_prefill_fn`` plus an [1, bucket, D] inject buffer substituted at
    masked positions before the transformer stack."""
    ps = ecfg.page_size

    def prefill(params, k_pages, v_pages, tokens, inject, inj_mask, length, page_table_row):
        positions = jnp.arange(bucket, dtype=jnp.int32)[None]
        pos = positions[0]
        in_range = pos < length
        logits, (ks, vs) = llama.forward_impl(
            params, cfg, tokens, positions, attn_impl=ecfg.prefill_impl,
            mesh=mesh, embeds_override=(inject, inj_mask),
            valid_mask=in_range[None],
        )
        page_ids = jnp.where(in_range, page_table_row[pos // ps], 0)
        slot_ids = pos % ps
        k_pages = _write_pages(k_pages, jnp.swapaxes(ks[:, 0], 0, 1), page_ids, slot_ids)
        v_pages = _write_pages(v_pages, jnp.swapaxes(vs[:, 0], 0, 1), page_ids, slot_ids)
        last = logits[0, length - 1]
        return last, k_pages, v_pages

    return jax.jit(prefill, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _suffix_prefill_fn(cfg: LlamaConfig, ecfg: EngineConfig, bucket: int):
    """Prefill `n_new` suffix tokens starting at absolute position `start`,
    attending over the session's CACHED pages as well as the chunk's own
    keys (prefix-cache hit path: only the suffix pays prefill FLOPs).

    The chunk runs as ragged rows of the autotuned ``block_q`` width (one
    row covering the whole bucket by default): the kernel streams the cached
    pages HBM→VMEM, serves intra-chunk causality from its same-launch
    new-key phase, and writes the chunk's K/V into the pool in the same
    launch — there is no separate scatter step and no per-layer
    [max_context] gather on the kernel path."""
    from agentfield_tpu.ops.pallas.kernel_autotune import lookup_blocks

    W = min(
        lookup_blocks(
            ecfg.page_size, cfg.head_dim, bucket, ecfg.kv_quant_dtype
        ).block_q,
        bucket,
    )
    R = -(-bucket // W)
    n_pad = R * W - bucket

    def prefill(params, k_pages, v_pages, tokens, start, n_new, page_table_row):
        positions = (start + jnp.arange(bucket, dtype=jnp.int32))[None]  # [1, B]
        x = llama.embed_tokens(params, cfg, tokens)
        cos, sin = llama.rope_sincos(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        rel = jnp.arange(bucket, dtype=jnp.int32)
        in_range = rel < n_new
        tables = jnp.broadcast_to(page_table_row[None], (R, page_table_row.shape[0]))
        row_starts = start + jnp.arange(R, dtype=jnp.int32) * W
        n_toks = jnp.clip(n_new - jnp.arange(R, dtype=jnp.int32) * W, 0, W)
        ctx_lens = jnp.full((R,), start, jnp.int32)
        seq_ids = jnp.zeros((R,), jnp.int32)

        def as_rows(t):  # [1, bucket, ...] → [R, W, ...]
            t = t[0]
            if n_pad:
                t = jnp.pad(t, ((0, n_pad),) + ((0, 0),) * (t.ndim - 1))
            return t.reshape((R, W) + t.shape[1:])

        def body(x, xs):
            lp, kp, vp = xs
            h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, k, v = llama.qkv_proj(lp, h, cfg, cos, sin)
            attn, kp, vp = ragged_paged_attention(
                as_rows(q), as_rows(k), as_rows(v), kp, vp, tables,
                row_starts, n_toks, ctx_lens, seq_ids,
                impl=ecfg.chunk_attn_impl,
                window=_binding_window(cfg, ecfg),
            )
            attn = attn.reshape(R * W, cfg.num_heads, cfg.head_dim)[:bucket][None]
            x = x + (attn.reshape(1, bucket, -1) @ lp["wo"]).astype(x.dtype)
            x = x + llama.mlp_block(lp, x, cfg, in_range[None])
            return x, (kp, vp)

        x, (kp, vp) = jax.lax.scan(body, x, (params["layers"], k_pages, v_pages))
        logits = llama.unembed(params, cfg, x)
        last = logits[0, n_new - 1]
        return last, kp, vp

    return jax.jit(prefill, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _mixed_step_fn(cfg: LlamaConfig, ecfg: EngineConfig, bucket: int, mesh=None):
    """Jitted MIXED token-budget tick (docs/MIXED_SCHEDULING.md): ONE ragged
    forward over ``mixed_step_budget`` packed tokens, each its own
    n_tokens=1 row — decode tokens (one per active slot, at its sequence's
    next position) and prefill-chunk tokens (consecutive positions of an
    admitting prompt, sharing a launch-local ``seq_id``) are the ragged
    paged-attention kernel's NATIVE input (``pack_ragged_rows``). The kernel
    fuses the multi-row KV write into the launch; a chunk's later tokens see
    its earlier ones through the kernel's same-launch new-key phase, so
    causal masking within a chunk is exact with no pre-scatter. Every
    position's logits are sampled with per-token params (the host reads only
    the rows it needs: decode rows, and a chunk's last token when it
    completes the prompt). One compile per ``bucket``
    (EngineConfig.mixed_bucket widths up to the budget) — the whole
    prefill-bucket x decode-bucket matrix collapses to this one ladder."""
    N = bucket

    def mixed(
        params, k_pages, v_pages, tokens, page_tables, row_starts, n_toks,
        ctx_lens, seq_ids, rng, temps, top_ks, top_ps,
    ):
        # tokens [N, 1]; page_tables [N, maxp]; row_starts/n_toks/ctx_lens/
        # seq_ids [N] — pack_ragged_rows' W=1 descriptor (n_toks == 0 marks
        # padding; a chunk's rows share seq_id and its ctx_len).
        x = llama.embed_tokens(params, cfg, tokens[:, 0])[:, None, :]  # [N,1,D]
        cos, sin = llama.rope_sincos(
            row_starts[:, None], cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
        )

        def body(x, xs):
            lp, kp, vp = xs
            h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, k, v = llama.qkv_proj(lp, h, cfg, cos, sin)  # [N, 1, ...]
            attn, kp, vp = ragged_paged_attention(
                q, k, v, kp, vp, page_tables, row_starts, n_toks, ctx_lens,
                seq_ids, impl=ecfg.chunk_attn_impl, mesh=mesh,
                window=_binding_window(cfg, ecfg),
            )
            x = x + (attn.reshape(N, 1, -1) @ lp["wo"]).astype(x.dtype)
            x = x + llama.mlp_block(lp, x, cfg)
            return x, (kp, vp)

        x, (kp, vp) = jax.lax.scan(body, x, (params["layers"], k_pages, v_pages))
        logits = llama.unembed(params, cfg, x)[:, 0]  # [N, V]
        toks = sample_tokens(logits, rng, temps, top_ks, top_ps)
        lps = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), toks[:, None], axis=-1
        )[:, 0]
        return toks, lps, kp, vp

    return jax.jit(mixed, donate_argnums=(1, 2))


# Fault-injector probe without importing the HTTP-heavy control_plane
# package into every engine process; ONE definition (kv_cache._kv_fault,
# shared with the offload worker's kv.* points) so the activation contract
# cannot drift between the scheduler's and the pool's consultations.
_engine_fault = _kv_fault


def _setup_compile_cache(ecfg: EngineConfig) -> None:
    """Wire the persistent JAX compilation cache (warm restarts skip the
    multi-second compile gate). Resolution: EngineConfig.compile_cache_dir,
    else $AGENTFIELD_COMPILE_CACHE, else leave jax's current setting alone
    (tests point it at their own directory). Logs the entry count found —
    a nonzero count at startup means the restart is warm."""
    import os

    path = ecfg.compile_cache_dir or os.environ.get("AGENTFIELD_COMPILE_CACHE")
    if not path:
        return
    try:
        entries = len(os.listdir(path)) if os.path.isdir(path) else 0
    except OSError:
        entries = 0
    jax.config.update("jax_compilation_cache_dir", path)
    from agentfield_tpu.logging import get_logger

    get_logger("engine").info(
        "jax compilation cache enabled",
        dir=path,
        entries_found=entries,
        warm=entries > 0,
    )


class QueueFullError(Exception):
    """Admission queue at capacity — surfaced as backpressure (the reference
    returns HTTP 503 from the async gateway, execute.go:333-346)."""


class GrammarCapacityError(Exception):
    """The engine's grammar bank has no room for another schema's states."""


class RequestTooLongError(Exception):
    pass


class InferenceEngine:
    def __init__(
        self,
        params: Any,
        cfg: LlamaConfig,
        ecfg: EngineConfig | None = None,
        seed: int = 0,
        mesh=None,
        draft: tuple[Any, LlamaConfig] | None = None,  # (params, cfg) of the
        # speculative-decoding draft model (required when ecfg.spec_k > 0;
        # must share the target's vocabulary)
    ):
        """With `mesh`, the engine runs tensor-parallel: params shard per the
        Megatron-style PartitionSpecs (parallel/sharding.py), KV pages over
        the KV-head axis; XLA inserts the ICI collectives (north-star config
        5: 70B TP=8). The scheduler/host side is unchanged — SPMD is invisible
        to it."""
        self.cfg = cfg
        self.ecfg = ecfg or EngineConfig()
        # Normalize the "auto" knobs ONCE so every jit cache key (the ecfg is
        # part of the lru_cache key) sees resolved values.
        if self.ecfg.kv_write_impl is not None:
            raise ValueError(
                f"EngineConfig.kv_write_impl={self.ecfg.kv_write_impl!r} was "
                "removed: the ragged kernel fuses the decode KV write into "
                "the attention launch — set attn_impl='pallas' to run the "
                "kernel path (docs/KERNELS.md)"
            )
        if self.ecfg.chunk_attn_impl == "auto":
            resolved = (
                "pallas"
                if (
                    self.ecfg.attn_impl == "pallas"
                    or self.ecfg.prefill_impl == "flash"
                )
                else "ref"
            )
            self.ecfg = dataclasses.replace(self.ecfg, chunk_attn_impl=resolved)
        if self.ecfg.chunk_attn_impl not in ("pallas", "ref"):
            raise ValueError(
                f"chunk_attn_impl={self.ecfg.chunk_attn_impl!r} must be "
                "'auto', 'pallas', or 'ref'"
            )
        if self.ecfg.attn_impl not in ("pallas", "ref"):
            raise ValueError(
                f"attn_impl={self.ecfg.attn_impl!r} must be 'pallas' or 'ref'"
            )
        from agentfield_tpu.ops.kv_quant import (
            KV_QUANT_DTYPES,
            quant_mode_supported,
        )

        if self.ecfg.kv_quant_dtype not in KV_QUANT_DTYPES:
            raise ValueError(
                f"kv_quant_dtype={self.ecfg.kv_quant_dtype!r} must be one of "
                f"{KV_QUANT_DTYPES}"
            )
        if not quant_mode_supported(self.ecfg.kv_quant_dtype):
            raise ValueError(
                f"kv_quant_dtype={self.ecfg.kv_quant_dtype!r} is not "
                "supported by this jax build (no float8_e4m3fn) — use "
                "'int8' or 'none'"
            )
        if self.ecfg.prefill_chunk is None and self.ecfg.chunk_attn_impl == "pallas":
            # Long prompts default onto the chunk kernel instead of the
            # gather fallback (the kernel caps at 512-wide chunks).
            self.ecfg = dataclasses.replace(
                self.ecfg, prefill_chunk=min(512, self.ecfg.max_context)
            )
        if self.ecfg.prefill_chunk is not None and self.ecfg.prefill_chunk < 16:
            raise ValueError(
                f"prefill_chunk={self.ecfg.prefill_chunk} must be >= 16 (one tile) or None"
            )
        if self.ecfg.decode_span < 1:
            raise ValueError(f"decode_span={self.ecfg.decode_span} must be >= 1")
        if self.ecfg.mixed_step not in (True, False, "auto"):
            raise ValueError(
                f"mixed_step={self.ecfg.mixed_step!r} must be True, False, "
                "or 'auto'"
            )
        if self.ecfg.mixed_step == "auto":
            # Speculative decode owns its ticks (draft+verify is already a
            # multi-token dispatch); auto turns mixing on everywhere else.
            self.ecfg = dataclasses.replace(
                self.ecfg, mixed_step=self.ecfg.spec_k == 0
            )
        if self.ecfg.mixed_step and self.ecfg.spec_k > 0:
            raise ValueError(
                "mixed_step=True is incompatible with spec_k > 0 "
                "(speculative decoding owns the tick); use mixed_step='auto' "
                "to fall back automatically"
            )
        if self.ecfg.mixed_step and (
            self.ecfg.mixed_step_budget < self.ecfg.max_batch + 16
        ):
            raise ValueError(
                f"mixed_step_budget={self.ecfg.mixed_step_budget} must be >= "
                f"max_batch+16={self.ecfg.max_batch + 16}: a full decode "
                "batch must still leave prefill-chunk room in the tick"
            )
        _setup_compile_cache(self.ecfg)
        if self.ecfg.max_pages_per_seq > self.ecfg.num_pages - 1:
            raise ValueError(
                f"max_pages_per_seq={self.ecfg.max_pages_per_seq} cannot exceed "
                f"num_pages-1={self.ecfg.num_pages - 1} (page 0 is reserved); "
                "an admitted request could otherwise never obtain its pages"
            )
        if cfg.moe_impl != "dense":
            raise ValueError(
                f"engine model cfg has moe_impl={cfg.moe_impl!r}: the DECODE "
                "path always soft-routes (weight-bound, exact) and takes no "
                "padding mask — use EngineConfig.moe_prefill_impl='sparse' "
                "to run sparse dispatch on prefill forwards"
            )
        if self.ecfg.moe_prefill_impl not in ("dense", "sparse"):
            raise ValueError(
                f"moe_prefill_impl={self.ecfg.moe_prefill_impl!r} must be "
                "'dense' or 'sparse'"
            )
        # Prefill forwards may run the sparse-dispatch MoE (compute-bound
        # phase); decode always soft-routes (weight-bound, exact). The
        # prefill builders are keyed on this cfg, so the flip costs nothing
        # when it is the identity.
        self.prefill_cfg = _sparse_prefill_cfg(cfg, self.ecfg)
        self.mesh = mesh
        if mesh is not None:
            from agentfield_tpu.parallel.mesh import AXIS_MODEL, AXIS_SEQ
            from agentfield_tpu.parallel.sharding import check_divisibility, shard_params

            if self.ecfg.prefill_impl == "ring":
                # Pure config checks first — rejecting AFTER shard_params
                # would pay a full 70B weight placement for nothing.
                sp = mesh.shape.get(AXIS_SEQ, 1)
                if sp < 2:
                    raise ValueError(
                        "prefill_impl='ring' needs a mesh with a 'seq' axis "
                        f"of size >= 2 (got axes {dict(mesh.shape)})"
                    )
                # Every prefill bucket (powers of two >= 16, clamped to
                # max_context) must divide by the seq axis, else the first
                # long request dies mid-tick in ring_attention.
                if sp & (sp - 1) or sp > 16 or self.ecfg.max_context % sp:
                    raise ValueError(
                        f"seq axis size {sp} must be a power of two <= 16 "
                        f"dividing max_context={self.ecfg.max_context} "
                        "(prefill buckets are powers of two >= 16)"
                    )
            tp = mesh.shape.get(AXIS_MODEL, 1)
            from agentfield_tpu.parallel.mesh import AXIS_EXPERT as _AE

            ep = mesh.shape.get(_AE, 1)
            if ep > 1 and cfg.num_experts % ep:
                # Fail at config time with a readable error, not inside
                # device_put (mirrors check_divisibility for TP).
                raise ValueError(
                    f"expert axis {ep} does not divide "
                    f"num_experts={cfg.num_experts}"
                )
            if tp > 1 or (ep > 1 and cfg.num_experts > 0):
                # Pallas impls run under shard_map over the (KV-)head axis —
                # see ops/paged_attention.py and models/llama.py attend() — so
                # TP composes with both the ref GSPMD path and the kernels
                # (north-star config 5: 70B TP=8 on the paged kernel).
                # EP-only meshes must shard too: replicating 8 experts per
                # device is exactly the OOM expert parallelism exists to avoid.
                if tp > 1:
                    check_divisibility(cfg, tp, paged_kv=True)
                params = shard_params(params, cfg, mesh)
        elif self.ecfg.prefill_impl == "ring":
            raise ValueError("prefill_impl='ring' requires a mesh (sequence-parallel)")
        self.params = params
        # KV pages must match the params' compute dtype (f32 params writing
        # into bf16 pages is a lossy scatter and a future jax error).
        cache_dtype = self.ecfg.dtype or str(
            jax.tree.leaves(params)[0].dtype if jax.tree.leaves(params) else cfg.dtype
        )
        self.cache = PagedKVCache.create(
            cfg, self.ecfg.num_pages, self.ecfg.page_size, cache_dtype,
            mesh=mesh, kv_quant=self.ecfg.kv_quant_dtype,
        )
        # Dense-twin page bytes (what a bf16/f32 pool at the same geometry
        # would cost): the yardstick for every kv_quant_*_saved counter —
        # HBM (pool.alloc), host store (demote/adopt), and wire
        # (model_node.kv_export_pages reads these attrs).
        _dense_dt = llama.resolve_dtype(cache_dtype)
        self.kv_page_bytes_dense = (
            2 * cfg.num_layers * cfg.num_kv_heads
            * self.ecfg.page_size * cfg.head_dim * jnp.dtype(_dense_dt).itemsize
        )
        self.kv_page_bytes = self.cache.page_bytes()
        # Speculative decoding: the draft model mirrors the target's page
        # TABLE (one allocator governs both) with its own page pool sized by
        # the draft config. Prefills replay onto the draft cache so proposals
        # see the full context.
        self.draft_params = self.draft_cfg = self.draft_cache = None
        if self.ecfg.spec_k > 0:
            if draft is None:
                raise ValueError(
                    f"spec_k={self.ecfg.spec_k} needs a draft model: "
                    "InferenceEngine(draft=(params, cfg))"
                )
            self.draft_params, self.draft_cfg = draft
            if self.draft_cfg.moe_impl != "dense":
                raise ValueError(
                    f"draft cfg has moe_impl={self.draft_cfg.moe_impl!r}: "
                    "draft decode soft-routes like the target's — use "
                    "EngineConfig.moe_prefill_impl='sparse' instead"
                )
            if self.draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {self.draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size} (speculation compares token ids)"
                )
            if mesh is not None:
                from agentfield_tpu.parallel.mesh import AXIS_MODEL as _AM
                from agentfield_tpu.parallel.sharding import (
                    check_divisibility as _chk,
                    shard_params as _shard,
                )

                dtp = mesh.shape.get(_AM, 1)
                if dtp > 1:
                    # The draft runs under the same mesh: its dims (incl. KV
                    # heads — the draft cache shards over them) must divide
                    # too, and its params shard like the target's.
                    _chk(self.draft_cfg, dtp, paged_kv=True)
                    self.draft_params = _shard(self.draft_params, self.draft_cfg, mesh)
            self.draft_cache = PagedKVCache.create(
                self.draft_cfg, self.ecfg.num_pages, self.ecfg.page_size,
                cache_dtype, mesh=mesh, kv_quant=self.ecfg.kv_quant_dtype,
            )
        self.draft_prefill_cfg = (
            _sparse_prefill_cfg(self.draft_cfg, self.ecfg)
            if self.draft_cfg is not None else None
        )
        # Counters (exported via the control plane's /metrics, mirroring the
        # reference's gateway gauges, internal/services/execution_metrics.go:14-44).
        # Created BEFORE the page pool: the pool increments its
        # prefix_pages_* counters directly into this dict.
        self.stats = {
            "prefill_tokens": 0,
            "decode_tokens": 0,
            "decode_steps": 0,
            "requests_finished": 0,
            "backpressure_total": 0,
            "prefix_cache_hits": 0,
            "prefix_tokens_reused": 0,
            "sessions_evicted": 0,
            "requests_cancelled": 0,
            "prefill_batches": 0,
            "admission_reorders": 0,
            "grammar_evictions": 0,
            "grammar_capacity_errors": 0,
            "spec_steps": 0,  # speculative dispatches
            "spec_emitted": 0,  # tokens emitted by them (rate = emitted /
            # (steps * (spec_k+1)))
            # Mixed token-budget scheduling (docs/MIXED_SCHEDULING.md):
            "mixed_ticks": 0,  # ticks that ran the packed ragged forward
            "mixed_tokens": 0,  # real tokens those ticks carried (decode +
            # prefill-chunk; utilization = mixed_tokens / (ticks * budget))
            # Cross-request shared-prefix cache (kv_cache.PrefixPagePool):
            "prefix_index_hits": 0,  # admissions that reused indexed pages
            "prefix_index_misses": 0,  # matchable fresh admissions that found none
            "prefix_cow_copies": 0,  # shared pages privatized (copied) before a write
            "prefix_pages_unpublished": 0,  # sole-holder indexed pages whose
            # mapping was dropped so the owner could write them in place
            "prefix_batch_deferrals": 0,  # batch mates deferred to reuse a
            # tick-mate's about-to-be-published prefix instead of re-prefilling
            # Failure-domain hardening (docs/FAULT_TOLERANCE.md):
            "deadline_exceeded": 0,  # requests cancelled by Request.deadline_s
            "cancels_unknown": 0,  # request_cancel of an id the engine does
            # not hold (already finished / never submitted): client and
            # engine disagree about in-flight work — worth an operator's eye
            "page_pressure_injected": 0,  # fault-injected allocation denials
            "drains_total": 0,  # graceful drains started (model node SIGTERM)
            "drain_cancelled": 0,  # requests deadline-outed by a drain
            # Overload control (docs/FAULT_TOLERANCE.md overload section):
            "preemptions_total": 0,  # active slots preempted for a starved
            # higher-priority request (KV parked in the prefix index, the
            # request re-queued with its generated suffix — no terminal event)
            "resume_prefix_hits_total": 0,  # preempted-request resumes that
            # re-admitted through a cached prefix instead of recomputing —
            # this staying ~= preemptions_total is the proof the preempt/
            # resume cycle rides the cache, not a re-prefill
            "shed_pending_deadline_total": 0,  # PENDING requests shed because
            # their deadline expired before they ever admitted (subset of
            # deadline_exceeded; queue-time overload signal)
            "preempt_storm_injected": 0,  # forced preemptions from the
            # engine.preempt_storm fault point (chaos testing)
            "spec_fail_injected": 0,  # spec.fail fault vetoes (keep-warm
            # only — the rung every speculation failure degrades to)
            "spec_stall_injected": 0,  # spec jobs deferred by the
            # spec.stall fault point (drained after delay_s, chaos testing)
            # Branch decoding (docs/PREFIX_CACHING.md "Fork / COW
            # branches") — always present so the stats→heartbeat→/metrics
            # pipeline carries the family even on nodes that never branch:
            "branch_forks_total": 0,  # sibling slots forked (install-time
            # N-way forks + beam reforks) — each shared the parent's full
            # KV pages instead of re-prefilling
            "branch_forks_degraded_total": 0,  # install-time forks that
            # found no slot/pages and fell back to the pending queue (the
            # sibling re-admits through the prefix index — correct, just
            # not free); a sustained nonzero means branch fan-out exceeds
            # engine capacity (docs/OPERATIONS.md "Branch decoding")
            "branch_fork_failed_total": 0,  # live reforks (beam) refused —
            # source finished or no capacity; the group continues narrower
            "branch_pruned_total": 0,  # branches cancelled by a pruning
            # policy (their pages freed through the request_cancel path)
            "branch_verifier_calls_total": 0,  # group resolutions scored by
            # a control-plane verifier reasoner instead of logprob sum
            # Disaggregated prefill/decode pools (docs/OPERATIONS.md
            # "Disaggregated pools") — always present so the stats→
            # heartbeat→/metrics pipeline carries the family even on
            # mixed-only fleets that never hand off:
            "kv_handoff_initiated_total": 0,  # phase-1 prefills that ended
            # in a handoff terminal (tail + sampler state stashed for export)
            "kv_handoff_completed_total": 0,  # phase-2 admissions installed
            # LIVE from an adopted tail (zero prefill on the decode node)
            "kv_handoff_failed_total": 0,  # handoff attempts that degraded
            # to ordinary single-node prefill+decode — export declined,
            # tail fetch/adopt failed, or the prefix walk fell short; the
            # request still completes token-exact, this counts the fallback
            "kv_handoff_bytes_total": 0,  # raw tail-payload bytes served
            # by the phase-1 node (the wire cost of live-slot handoff)
            # failed_total split by cause, the first question a fallback
            # spike raises (docs/OPERATIONS.md "Disaggregated pools"):
            "kv_handoff_fail_walk_total": 0,  # prefix walk fell short of
            # the full prompt (adoption missing/evicted, restore declined)
            "kv_handoff_fail_stash_total": 0,  # tail payload absent or
            # aged out of the inbound stash at admission time
            "kv_handoff_fail_upload_total": 0,  # tail-page device upload
            # raised — pool donated mid-install or backend error
            "kv_handoff_fail_export_total": 0,  # phase-1 export declined
            # (ineligible request, injected fault, D2H capture failure)
            # Agent-aware serving (docs/OPERATIONS.md "Agent-aware
            # serving") — always present so the stats→heartbeat→/metrics
            # pipeline carries the family even on fleets that never send
            # expect_followup. These count speculative next-step PREFILL
            # jobs, not speculative decoding (spec_steps/spec_emitted):
            "spec_started_total": 0,  # speculative prefill jobs enqueued
            # (one per declared candidate that passed the caps)
            "spec_hit_total": 0,  # follow-up admissions that absorbed a
            # speculated prefix from the index — TTFT paid only the suffix
            "spec_wasted_tokens_total": 0,  # candidate tokens prefilled for
            # losers (the price of speculation; see the wasted-tokens
            # budget guidance in docs/OPERATIONS.md)
            "spec_cancelled_total": 0,  # speculative jobs cancelled or
            # their stashed pages dropped (losers at absorb, pin expiry/
            # spill, client cancel of the parent session)
            "session_pins_active": 0,  # GAUGE: sessions currently
            # keep-warm-pinned awaiting a follow-up (bounded by
            # spec_pin_budget; assigned, not incremented)
        }
        # Cross-request sharing rides on the session prefix-cache switch: one
        # knob (enable_prefix_cache=False) turns ALL KV reuse off for A/B runs.
        self._shared_prefix = bool(
            self.ecfg.enable_prefix_cache and self.ecfg.shared_prefix_cache
        )
        # The pool itself is lock-free (kv_cache.py declares its innards
        # `guarded by: external(...)`): THIS lock is the serializer.
        self.allocator = PrefixPagePool(  # guarded by: _session_lock
            self.ecfg.num_pages, self.ecfg.page_size, stats=self.stats
        )
        if self.ecfg.kv_quant_dtype != "none":
            # Arm the kv_quant_* counters: every page the pool hands out
            # stores quantized KV, saving (dense - quant) bytes vs the
            # bf16 twin in HBM and in the host store alike.
            self.allocator.configure_quant(
                max(0, self.kv_page_bytes_dense - self.kv_page_bytes)
            )
        # Per-pending-request prompt chain hashes, computed once: the
        # admission probe runs every tick over the whole window, and
        # re-hashing long prompts each tick would tax the decode loop.
        # Entries drop at admission/cancel.
        self._req_hashes: dict[str, list[bytes]] = {}
        # Live-slot handoff stashes (docs/ARCHITECTURE.md "Two-phase
        # dispatch"). _handoff_out: phase-1 exports awaiting the decode
        # node's tail fetch — request id → (expiry, descriptor, host tail
        # payload). _handoff_in: adopted tail payloads awaiting their
        # phase-2 admission — handoff id → (expiry, payload). Both are
        # TTL-bounded and size-capped so an orphaned entry (decode pool
        # died mid-handoff, phase-2 shed from the queue) ages out instead
        # of pinning host page copies forever; an aged-out entry just
        # means the other side re-prefills, token-exact.
        self._handoff_out: dict[str, tuple[float, dict, Any]] = {}  # guarded by: _session_lock
        self._handoff_in: dict[str, tuple[float, Any]] = {}  # guarded by: _session_lock
        B, maxp = self.ecfg.max_batch, self.ecfg.max_pages_per_seq
        self.page_tables = np.zeros((B, maxp), np.int32)
        self.seq_lens = np.zeros((B,), np.int32)
        self.last_tokens = np.zeros((B,), np.int32)
        self.temps = np.zeros((B,), np.float32)
        self.top_ks = np.zeros((B,), np.int32)
        self.top_ps = np.ones((B,), np.float32)
        # Constrained decoding (grammar_slots > 0): per-slot bank-global DFA
        # state (0 = unconstrained) + per-slot stop-id rows (-1 padded); the
        # transition bank is host-built (rows shifted to bank-global ids) and
        # device-mirrored with row-range incremental uploads. int16 keeps the
        # bank at 2 bytes/entry (state ids are bounded by grammar_slots).
        self.grammar_states = np.zeros((B,), np.int32)
        self.eos_ids = np.full((B, _MAX_STOP_IDS), -1, np.int32)
        S = max(1, self.ecfg.grammar_slots)
        if S > np.iinfo(np.int16).max:
            raise ValueError(f"grammar_slots={S} exceeds int16 bank capacity")
        self._gbank_trans = np.zeros((S, cfg.vocab_size), np.int16)  # row 0: free
        self._gbank_accept = np.zeros((S,), bool)
        self._gbank_accept[0] = True
        # Entries hold a STRONG reference to each Grammar: the id() key stays
        # valid, and refcounts gate eviction (rows of a grammar still used by
        # a pending/slotted request must never be reallocated).
        self._gbank_entries: dict[int, dict[str, Any]] = {}
        self._gbank_free: list[tuple[int, int]] = [(1, S - 1)] if S > 1 else []
        self._gbank_dev: dict[str, jax.Array] | None = None
        self._gbank_dirty_rows: list[tuple[int, int]] = []  # (offset, n) to upload
        self._gbank_clock = 0.0  # LRU tiebreaker for eviction
        self.slots: list[_Slot | None] = [None] * B
        self.pending: collections.deque[Request] = collections.deque()
        self._sessions: dict[str, _SessionEntry] = {}  # guarded by: _session_lock
        # Cancellation requests (thread-safe set): drained inside step() on
        # the worker thread — mutating slots from other threads mid-step
        # would race the decode batch.
        self._cancels: set[str] = set()
        # Live-fork commands (branch decoding): (src_id, new_id) pairs from
        # request_fork(), applied inside step() on the scheduler thread —
        # cloning a slot from another thread would race the decode batch.
        # Guarded by _pending_lock (same cross-thread discipline as
        # _deadline_at).
        self._fork_cmds: list[tuple[str, str]] = []  # guarded by: _pending_lock
        # Request deadlines: id -> monotonic expiry (written at submit under
        # _pending_lock, scanned at the top of step()). Expired ids cancel
        # through the normal _cancels path and emit a terminal
        # finish_reason="deadline_exceeded" event — including ids still in
        # the PENDING queue, which shed without ever occupying a slot.
        self._deadline_at: dict[str, float] = {}  # guarded by: _pending_lock
        # Drain sweep flag (deadline_all_now): applied on the scheduler
        # thread at the next step so live-request enumeration cannot race.
        self._drain_sweep = False
        # step() runs on a worker thread (ModelBackend) while submit()/
        # free_session() run on the event loop: session+allocator mutations
        # need mutual exclusion.
        self._session_lock = threading.RLock()
        # Tiered KV (docs/PREFIX_CACHING.md "Tiered cache"): a host-RAM
        # second tier under the shared-prefix pool. The pool owns the tier
        # state and the offload worker; the engine supplies the three
        # device-copy callbacks and its _session_lock as the serializer.
        if self.ecfg.host_cache_bytes > 0:
            if not self._shared_prefix:
                raise ValueError(
                    f"host_cache_bytes={self.ecfg.host_cache_bytes} requires "
                    "enable_prefix_cache and shared_prefix_cache: the host "
                    "tier is content-addressed"
                )
            # Quantized pools press host_cache_bytes at ~half the dense
            # rate (page_bytes includes the per-slot scales), so the same
            # budget holds ~2x the demoted pages — the tier-capacity half
            # of the kv_quant_dtype win (docs/PREFIX_CACHING.md).
            page_bytes = self.kv_page_bytes
            self.allocator.enable_host_tier(
                budget_bytes=self.ecfg.host_cache_bytes,
                page_bytes=page_bytes,
                lock=self._session_lock,
                capture=self._capture_page_kv,
                fetch=_fetch_page_kv,
                upload=self._upload_page_kv,
                # Restore targets come from the session-evicting allocator:
                # a pool fully pinned by idle LIVE sessions must still
                # restore (the resume it serves is a live request — it
                # wins over cached prefixes, same rule as admission).
                restore_alloc=lambda: self._alloc_with_eviction(1),
            )
        elif self._shared_prefix:
            # No local demotion tier, but the CLUSTER tier still needs the
            # restore half armed: peer-fetched pages (adopt_kv_pages) land in
            # the pool's host store and restore at admission exactly like a
            # demoted page would (docs/PREFIX_CACHING.md "Cluster tier").
            # The budget is a transfer staging buffer, not a cache — sized
            # to TWO admission windows of full prefixes (floor 32 pages):
            # under a disaggregated phase-2 burst every queued request
            # adopts its whole prompt before ANY of them admits, and an
            # undersized buffer evicts the oldest adoption before its
            # owner reaches the prefix walk — a silent full re-prefill.
            page_bytes = self.kv_page_bytes
            staging_pages = max(
                32, 2 * self.ecfg.max_batch * self.ecfg.max_pages_per_seq
            )
            self.allocator.enable_restore(
                budget_bytes=staging_pages * page_bytes,
                page_bytes=page_bytes,
                upload=self._upload_page_kv,
                restore_alloc=lambda: self._alloc_with_eviction(1),
            )
        # Guards self.pending: submit() appends from the event-loop thread
        # while _drain_cancels() rebuilds the deque on the worker thread —
        # unguarded, an append during the rebuild raises RuntimeError or is
        # silently dropped (its future would never resolve).
        self._pending_lock = threading.Lock()
        self._rng = jax.random.PRNGKey(seed)
        self._decode_jit = _decode_fn(cfg, self.ecfg, mesh)
        # Device-resident copies of the control arrays; refreshed from the
        # numpy shadows only when admission/release dirties them.
        self._dirty = True
        self._dev: dict[str, jax.Array] = {}
        # Compact-decode device state, valid while the active-slot membership
        # is unchanged (admission/release invalidates it).
        self._compact: dict[str, Any] | None = None
        # One-deep decode pipeline: the dispatched-but-unread step (async_decode).
        self._inflight: dict[str, Any] | None = None
        # Consecutive ticks the queue head has been page-starved while later
        # requests admitted (see _try_admit's fairness fence).
        self._head_starved_ticks = 0
        # Consecutive ticks the best pending candidate has out-prioritized
        # the lowest-priority active slot while page/slot-starved; reaching
        # preempt_fence_ticks fires a preemption (_maybe_preempt). Scheduler-
        # thread state: only step() reads or writes it.
        self._preempt_starved_ticks = 0
        # Request id the preemption probe saw at the queue head last tick:
        # a head STILL pending one tick later was tried — and refused — by
        # admission in between, so it is starved regardless of what the
        # capacity arithmetic in _cand_starved can model (COW copies,
        # session re-allocs). Scheduler-thread state, like the counter.
        self._preempt_last_head: str | None = None
        # Mixed scheduling: admitting requests mid-chunked-prefill. Each job
        # reserves one decode slot BY COUNT (_slots_available) and installs
        # into a concrete slot when its prompt completes.
        self._prefill_jobs: list[_PrefillJob] = []
        # Scheduler-latency telemetry (scheduler_stats): rolling windows of
        # inter-token arrival gaps (seconds) and per-dispatch token counts.
        # The lock serializes worker-thread appends against event-loop reads
        # (heartbeats, /stats) — iterating a deque mid-append raises.
        self._telemetry_lock = threading.Lock()
        self._itl_window: collections.deque[float] = collections.deque(maxlen=4096)  # guarded by: _telemetry_lock
        self._tick_tokens: collections.deque[int] = collections.deque(maxlen=1024)  # guarded by: _telemetry_lock
        # Observability (docs/OBSERVABILITY.md). Always-on: fixed-bucket
        # latency histograms shipped on every heartbeat (real Prometheus
        # histograms fleet-wide, not just local percentile gauges) and the
        # flight recorder — a fixed ring of per-tick scheduler records,
        # served by the node debug endpoint and dumped on step failure.
        self.latency = tracing.HistogramSet(
            ("ttft_ms", "itl_ms", "queue_wait_ms", "tick_ms")
        )
        self.flight = tracing.FlightRecorder()
        self._tick_mode = "decode"  # scheduler-thread state, like the fences
        self._tick_carried = 0
        # Request-scoped tracing: per-request mark dicts (enqueue/prefill/
        # decode monotonic anchors + the trace id), present only for
        # requests that arrived with a valid TraceContext. Individual
        # get/set/pop per rid — the same GIL-atomic cross-thread discipline
        # as _cancels.
        self._tracer = tracing.tracer()
        self._traces: dict[str, dict] = {}
        # Submit-time monotonic stamps for EVERY request (traced or not):
        # the queue-wait and TTFT histograms read them at queue-exit and
        # first token. Entries pop at install or cancel.
        self._submit_t: dict[str, float] = {}
        # Agent-aware serving (docs/OPERATIONS.md "Agent-aware serving").
        # _pins: session id → pinned-at wall time; a pinned session is
        # skipped by gc_sessions and by the eviction ladder's first rung
        # until the follow-up admits or spec_pin_ttl expires.
        # _spec_by_session: session id → speculation state (parent id,
        # candidate suffixes by spec-job id, stashed page refs of finished
        # jobs, trace anchors) — the absorb/cancel bookkeeping for
        # speculative next-step prefills.
        self._pins: dict[str, float] = {}  # guarded by: _session_lock
        self._spec_by_session: dict[str, dict] = {}  # guarded by: _session_lock
        # Deferred speculative jobs (the spec.stall fault point): (ready-at
        # monotonic, request) pairs enqueued at the top of step() once ready.
        # Scheduler-thread state like the starvation fences — _release and
        # _step_inner both run there.
        self._spec_stalled: list[tuple[float, Request]] = []

    # ------------------------------------------------------------------
    # host-side scheduling
    # ------------------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Enqueue a request. Raises QueueFullError at capacity and
        RequestTooLongError if it can never fit the page budget."""
        if not req.prompt:
            raise ValueError(f"request {req.id}: prompt must be non-empty")
        if req.mm_embeds:
            D = self.cfg.hidden_size
            for off, emb in req.mm_embeds:
                arr = np.asarray(emb)
                if arr.ndim != 2 or arr.shape[1] != D:
                    raise ValueError(
                        f"request {req.id}: mm_embeds must be [k, {D}] arrays, "
                        f"got shape {arr.shape}"
                    )
                if off < 0 or off + arr.shape[0] > len(req.prompt):
                    raise ValueError(
                        f"request {req.id}: mm span [{off}, {off + arr.shape[0]}) "
                        f"outside the {len(req.prompt)}-token prompt"
                    )
        if req.grammar is not None:
            if self.ecfg.grammar_slots <= 0:
                raise ValueError(
                    f"request {req.id}: carries a grammar but the engine was "
                    "built with grammar_slots=0 (constrained decoding disabled)"
                )
            if not req.sampling.stop_token_ids:
                raise ValueError(
                    f"request {req.id}: grammar-constrained requests need "
                    "stop_token_ids — EOS is the only legal terminator once "
                    "the value is complete"
                )
            if len(req.sampling.stop_token_ids) > _MAX_STOP_IDS:
                # The decode-step EOS allowance is a fixed-width row; silently
                # truncating would mask some terminators forever and run the
                # request to max_new_tokens.
                raise ValueError(
                    f"request {req.id}: at most {_MAX_STOP_IDS} stop_token_ids "
                    f"are supported with a grammar (got "
                    f"{len(req.sampling.stop_token_ids)})"
                )
        if req.deadline_s is not None and (
            not math.isfinite(req.deadline_s) or req.deadline_s <= 0
        ):
            # BEFORE _grammar_acquire below: a rejected request must never
            # pin bank rows. NaN is comparison-inert — it would slide past
            # every deadline sweep as a silent "no deadline".
            raise ValueError(
                f"request {req.id}: deadline_s={req.deadline_s} must be a "
                "positive finite number"
            )
        if type(req.n_branches) is not int or req.n_branches < 1:
            raise ValueError(
                f"request {req.id}: n_branches must be an int >= 1 "
                f"(got {req.n_branches!r})"
            )
        if req.n_branches > 1 and (req.grammar is not None or req.mm_embeds):
            # A mid-schema DFA state cannot be forked through first-token
            # re-sampling, and mm prompts are excluded from every KV-reuse
            # path the fork rides — both admit fine unbranched.
            raise ValueError(
                f"request {req.id}: n_branches > 1 is incompatible with "
                "grammar-constrained or multimodal requests"
            )
        if req.handoff is not None and not isinstance(req.handoff, dict):
            # Anything else about a malformed descriptor (wrong page_size,
            # wrong prompt length, unknown id) degrades at admission to a
            # normal token-exact prefill — only the type is load-bearing.
            raise ValueError(
                f"request {req.id}: handoff must be a descriptor dict "
                f"(got {type(req.handoff).__name__})"
            )
        if type(req.priority) is not int:  # bool included: True < 2 would
            # "work" but a flag is never a tier — and a non-int raising
            # inside _enqueue_locked AFTER _grammar_acquire would leak the
            # acquired bank row, so reject here with the other validations.
            raise ValueError(
                f"request {req.id}: priority must be an int "
                f"(got {type(req.priority).__name__})"
            )
        needed = self._pages_needed(req)
        if needed > self.ecfg.max_pages_per_seq:
            raise RequestTooLongError(
                f"request {req.id}: {len(req.prompt)} prompt + "
                f"{req.sampling.max_new_tokens} new tokens needs {needed} pages "
                f"> max_pages_per_seq={self.ecfg.max_pages_per_seq}"
            )
        if req.grammar is not None:
            # Acquire LAST so a rejected request never pins bank rows; may
            # raise GrammarCapacityError (after evicting idle grammars).
            with self._session_lock:
                self._grammar_acquire(req.grammar)
        try:
            with self._pending_lock:
                if len(self.pending) >= self.ecfg.max_pending:
                    self.stats["backpressure_total"] += 1
                    raise QueueFullError(
                        f"pending queue at capacity {self.ecfg.max_pending}"
                    )
                # Stamp BEFORE the enqueue: the scheduler thread may admit
                # and install the request the instant it lands in the
                # queue — a post-release stamp would miss the install's
                # pop (leaking the entry and mistiming the trace).
                self._submit_t[req.id] = time.monotonic()
                self._tr_submit(req)
                self._enqueue_locked(req)
                if req.deadline_s is not None:
                    self._deadline_at[req.id] = time.monotonic() + req.deadline_s
        except QueueFullError:
            with self._session_lock:
                self._grammar_release(req.grammar)
            raise

    def _enqueue_locked(self, req: Request, senior: bool = False) -> None:  # guarded by: _pending_lock
        """Insert into the PRIORITY-TIER-ORDERED pending queue (guarded by
        _pending_lock at every call site): the queue is kept non-increasing
        in priority, FIFO within a tier, so the head is always the oldest
        top-priority request — admission, the anti-starvation fence, and the
        preemption probe all see priority traffic without scanning past the
        window. Flat-priority traffic short-circuits to a plain append
        (bit-identical to the pre-priority queue). ``senior=True`` inserts
        at the FRONT of the request's tier instead of the back — a preempted
        victim keeps its seniority over later arrivals of its own tier."""
        p = req.priority
        if not senior and (not self.pending or self.pending[-1].priority >= p):
            self.pending.append(req)
            return
        for i, r in enumerate(self.pending):
            if (r.priority < p) if not senior else (r.priority <= p):
                self.pending.insert(i, req)
                return
        self.pending.append(req)

    def _pages_needed(self, req: Request) -> int:
        total = len(req.prompt) + req.sampling.max_new_tokens
        return -(-total // self.ecfg.page_size)

    # ------------------------------------------------------------------
    # request-scoped tracing (docs/OBSERVABILITY.md "Trace anatomy"):
    # lifecycle spans recorded on the EXISTING event paths — every helper
    # is a dict miss and early return for untraced requests.
    # ------------------------------------------------------------------

    def _tr_submit(self, req: Request) -> None:
        ctx = tracing.valid_context(req.trace)
        if ctx is None:
            return
        self._traces[req.id] = {
            "tid": ctx["trace_id"],
            "enq_w": time.time(),
            "enq_m": time.perf_counter(),
        }

    def _tr_dequeue(self, req: Request, start: int = 0) -> None:
        """Queue-exit (classic single, batch, or mixed-job creation): close
        the queue-wait span — or, for a preempted request re-admitting, the
        park span — and anchor the prefill span. ``start`` is the cached-
        prefix length prefill skips (the prefill span's ``cached`` attr)."""
        e = self._traces.get(req.id)
        if e is None:
            return
        now_m = time.perf_counter()
        parked = e.pop("parked", None)
        if parked is not None:
            self._tracer.record_span(
                "engine.park", e["tid"], parked[0], (now_m - parked[1]) * 1e3,
                {"resumed_tokens": req.resumed_from},
            )
        else:
            self._tracer.record_span(
                "engine.queue_wait", e["tid"], e["enq_w"],
                (now_m - e["enq_m"]) * 1e3,
            )
        e["pf_w"], e["pf_m"] = time.time(), now_m
        e["start"] = start

    def _tr_first_token(self, req: Request) -> None:
        """First sampled token: close the prefill span, anchor decode."""
        e = self._traces.get(req.id)
        if e is None:
            return
        now_m = time.perf_counter()
        pf_m = e.pop("pf_m", None)
        pf_w = e.pop("pf_w", None)
        if pf_m is not None:
            self._tracer.record_span(
                "engine.prefill", e["tid"], pf_w, (now_m - pf_m) * 1e3,
                {"tokens": len(req.prompt), "cached": e.pop("start", 0)},
            )
        e["dec_w"], e["dec_m"] = time.time(), now_m

    def _tr_close(self, rid: str, reason: str, generated: int | None = None) -> None:
        """Terminal (natural finish, cancel, deadline): close the decode
        span and drop the entry. A request that never decoded (shed from
        the queue) closes its queue-wait span instead — the waterfall shows
        it died waiting, which is the point of the trace."""
        e = self._traces.pop(rid, None)
        if e is None:
            return
        now_m = time.perf_counter()
        if e.get("dec_m") is not None:
            attrs = {"finish": reason}
            if generated is not None:
                attrs["tokens"] = generated
            self._tracer.record_span(
                "engine.decode", e["tid"], e["dec_w"], (now_m - e["dec_m"]) * 1e3,
                attrs,
            )
        elif e.get("pf_m") is None:
            parked = e.get("parked")
            t0w, t0m = (
                (parked[0], parked[1]) if parked else (e["enq_w"], e["enq_m"])
            )
            self._tracer.record_span(
                "engine.queue_wait", e["tid"], t0w, (now_m - t0m) * 1e3,
                {"finish": reason},
            )

    def _tr_preempt(self, slot: _Slot) -> None:
        """Preemption: close the current decode segment (labeled) and start
        the park clock — the resume path turns it into an ``engine.park``
        span at re-admission."""
        e = self._traces.get(slot.req.id)
        if e is None:
            return
        now_m = time.perf_counter()
        if e.get("dec_m") is not None:
            self._tracer.record_span(
                "engine.decode", e["tid"], e["dec_w"], (now_m - e["dec_m"]) * 1e3,
                {"finish": "preempted", "tokens": slot.generated},
            )
        for k in ("dec_m", "dec_w", "pf_m", "pf_w"):
            e.pop(k, None)
        e["parked"] = (time.time(), now_m)

    def _tr_fork(self, parent_id: str, child_id: str, degraded: bool = False) -> None:
        """Branch fork (install-time fan-out or live beam re-fork): the
        child inherits the parent's trace id so the whole group — winner
        and pruned branches alike — lands in ONE waterfall."""
        e = self._traces.get(parent_id)
        if e is None:
            return
        now_w, now_m = time.time(), time.perf_counter()
        attrs = {"branch": child_id}
        if degraded:
            attrs["degraded"] = 1
        self._tracer.record_span("engine.fork", e["tid"], now_w, 0.0, attrs)
        child = {"tid": e["tid"], "enq_w": now_w, "enq_m": now_m}
        if not degraded:
            # installs as a live batch-mate immediately: decode starts now
            child["dec_w"], child["dec_m"] = now_w, now_m
        self._traces[child_id] = child

    def grammar_bank_stats(self) -> dict[str, int]:
        """Capacity gauges for the constrained-decoding bank (VERDICT r2 item
        8): how close the int16 row bank is to exhaustion, how many grammars
        are resident, and how many are pinned by in-flight requests."""
        if self.ecfg.grammar_slots <= 0:  # constrained decoding disabled
            return {
                "grammar_bank_rows": 0,
                "grammar_bank_rows_free": 0,
                "grammar_bank_rows_used": 0,
                "grammar_bank_grammars": 0,
                "grammar_bank_grammars_in_use": 0,
            }
        with self._session_lock:  # acquire/release mutate the bank on the
            # event-loop and worker threads under this lock
            free = sum(s for _, s in self._gbank_free)
            usable = self.ecfg.grammar_slots - 1  # row 0 = unconstrained state
            return {
                "grammar_bank_rows": usable,
                "grammar_bank_rows_free": free,
                "grammar_bank_rows_used": usable - free,
                "grammar_bank_grammars": len(self._gbank_entries),
                "grammar_bank_grammars_in_use": sum(
                    1 for e in self._gbank_entries.values() if e["refs"] > 0
                ),
            }

    def _gbank_alloc_range(self, n: int) -> int | None:
        """First-fit over the free list (ranges never move, so active bank-
        global state ids stay valid across other grammars' lifecycles)."""
        for i, (off, size) in enumerate(self._gbank_free):
            if size >= n:
                if size == n:
                    self._gbank_free.pop(i)
                else:
                    self._gbank_free[i] = (off + n, size - n)
                return off
        return None

    def _gbank_free_range(self, off: int, n: int) -> None:
        self._gbank_free.append((off, n))
        # merge adjacent ranges to fight fragmentation
        self._gbank_free.sort()
        merged: list[tuple[int, int]] = []
        for o, s in self._gbank_free:
            if merged and merged[-1][0] + merged[-1][1] == o:
                merged[-1] = (merged[-1][0], merged[-1][1] + s)
            else:
                merged.append((o, s))
        self._gbank_free = merged

    def _grammar_acquire(self, g: Grammar) -> int:
        """Register (if new) and take a reference on a Grammar's bank rows.
        Under capacity pressure, unreferenced grammars evict LRU. Every
        acquire is balanced by a _grammar_release when the request leaves the
        engine (finished, cancelled, or failed admission)."""
        self._gbank_clock += 1.0
        ent = self._gbank_entries.get(id(g))
        if ent is not None:
            ent["refs"] += 1
            ent["used"] = self._gbank_clock
            return ent["off"]
        if g.trans.shape[1] != self.cfg.vocab_size:
            raise ValueError(
                f"grammar vocab {g.trans.shape[1]} != model vocab {self.cfg.vocab_size}"
            )
        n = g.n_states
        off = self._gbank_alloc_range(n)
        while off is None:
            idle = [k for k, e in self._gbank_entries.items() if e["refs"] <= 0]
            if not idle:
                self.stats["grammar_capacity_errors"] += 1
                # Loud signal, not just a counter: sustained capacity errors
                # mean grammar_slots is undersized for the schema mix
                # (VERDICT r4 weak #8) — the stat also rides heartbeats.
                from agentfield_tpu.logging import get_logger

                get_logger("engine").warning(
                    "grammar bank exhausted",
                    needed_states=n,
                    grammar_slots=self.ecfg.grammar_slots,
                    capacity_errors=self.stats["grammar_capacity_errors"],
                )
                raise GrammarCapacityError(
                    f"grammar needs {n} states; bank capacity "
                    f"{self.ecfg.grammar_slots} is exhausted by in-use grammars"
                )
            victim = min(idle, key=lambda k: self._gbank_entries[k]["used"])
            ve = self._gbank_entries.pop(victim)
            self._gbank_free_range(ve["off"], ve["n"])
            self.stats["grammar_evictions"] += 1
            off = self._gbank_alloc_range(n)
        self._gbank_trans[off : off + n] = np.where(
            g.trans >= 0, g.trans + off, -1
        ).astype(np.int16)
        self._gbank_accept[off : off + n] = g.accept
        self._gbank_entries[id(g)] = {
            "grammar": g,  # strong ref: keeps id() stable while registered
            "off": off,
            "n": n,
            "refs": 1,
            "used": self._gbank_clock,
        }
        self._gbank_dirty_rows.append((off, n))
        return off

    def _grammar_release(self, g: Grammar | None) -> None:
        if g is None:
            return
        ent = self._gbank_entries.get(id(g))
        if ent is not None and ent["refs"] > 0:
            ent["refs"] -= 1
        # rows stay cached (warm) until capacity pressure evicts them

    def _gbank_device(self) -> dict[str, jax.Array]:
        with self._session_lock:
            return self._gbank_device_locked()

    def _gbank_device_locked(self) -> dict[str, jax.Array]:
        if self._gbank_dev is None:
            self._gbank_dev = {
                "trans": jnp.asarray(self._gbank_trans),
                "accept": jnp.asarray(self._gbank_accept),
            }
            self._gbank_dirty_rows.clear()
        elif self._gbank_dirty_rows:
            # Upload only the newly written row ranges; the device-side
            # .at[].set copy is cheap next to a full-bank host transfer.
            trans, accept = self._gbank_dev["trans"], self._gbank_dev["accept"]
            for off, n in self._gbank_dirty_rows:
                trans = trans.at[off : off + n].set(
                    jnp.asarray(self._gbank_trans[off : off + n])
                )
                accept = accept.at[off : off + n].set(
                    jnp.asarray(self._gbank_accept[off : off + n])
                )
            self._gbank_dev = {"trans": trans, "accept": accept}
            self._gbank_dirty_rows.clear()
        return self._gbank_dev

    def _first_token_mask(self, req: Request) -> tuple[np.ndarray, int] | None:
        """Host-side mask for the token sampled from prefill logits. Returns
        (allowed [V] bool, bank offset) or None for unconstrained requests.
        The grammar already holds a reference (acquired at submit)."""
        if req.grammar is None:
            return None
        ent = self._gbank_entries[id(req.grammar)]
        row = req.grammar.trans[req.grammar.start]
        allowed = row >= 0
        if req.grammar.accept[req.grammar.start]:
            allowed = allowed.copy()
            allowed[list(req.sampling.stop_token_ids)] = True
        return allowed, ent["off"]

    def gc_sessions(self, at: float | None = None) -> int:
        """Release pages of sessions idle longer than session_ttl (eviction
        under pressure remains the primary mechanism; this bounds idle
        retention). Called opportunistically by the model-node drive loop.
        Keep-warm-pinned sessions (docs/OPERATIONS.md "Agent-aware
        serving") are exempt while their pin lives; a pin whose follow-up
        never arrived expires here after spec_pin_ttl — releasing any
        speculative pages — and the session rejoins the ordinary ttl clock."""
        t = at if at is not None else time.time()
        with self._session_lock:
            if self._pins:
                for sid in [
                    s for s, p in self._pins.items()
                    if t - p > self.ecfg.spec_pin_ttl
                ]:
                    self._unpin_session_locked(sid)
        ttl = self.ecfg.session_ttl
        if not ttl:
            return 0
        with self._session_lock:
            dead = [
                sid for sid, s in self._sessions.items()
                if t - s.last_used > ttl and sid not in self._pins
            ]
            demote: list[int] = []
            for sid in dead:
                pages = self._sessions.pop(sid).pages
                self.allocator.free(pages)
                self.stats["sessions_evicted"] += 1
                demote += pages
            if demote:
                # Idle-session expiry is the canonical demote trigger
                # (docs/PREFIX_CACHING.md "Tiered cache"): the session's
                # published pages just went refcount-0 — move them to host
                # RAM now so a later resume restores instead of
                # re-prefilling once churn evicts them. No-op with the
                # host tier off; partial tail pages (not indexed) skip.
                self.allocator.demote_pages(demote)
        return len(dead)

    def free_session(self, session_id: str) -> bool:
        """Explicitly drop a session's cached prefix (thread-safe vs step())."""
        with self._session_lock:
            # An explicit drop is a terminal for the session's agent program:
            # release its keep-warm pin and speculation state too (no-op
            # when unpinned) — a freed session must never keep pages warm.
            if session_id in self._pins or session_id in self._spec_by_session:
                self._unpin_session_locked(session_id)
            sess = self._sessions.pop(session_id, None)
            if sess is None:
                return False
            self.allocator.free(sess.pages)
            return True

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self.slots)

    def has_work(self) -> bool:
        return (
            bool(self.pending)
            or self.num_active > 0
            or self._inflight is not None
            or bool(self._prefill_jobs)
            # Queued live-fork commands need a step to apply (or to emit
            # their fork_failed terminal) — an idle drive loop must not
            # sleep through them.
            or bool(self._fork_cmds)  # afcheck: ignore[guarded-by] racy truthiness peek like _cancels: a missed append is caught by the next wake, never lost
            # Stalled speculative prefills (spec.stall chaos) need a step to
            # re-admit or cancel once their delay elapses.
            or bool(self._spec_stalled)
        )

    def _slots_available(self) -> int:
        """Free decode slots not reserved by in-flight prefill jobs: a job
        must always find a slot when its prompt completes, so admission (and
        new jobs) only claim what the jobs have not."""
        free = sum(s is None for s in self.slots)
        return free - len(self._prefill_jobs)

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _alloc_with_eviction(self, n: int) -> list[int] | None:  # guarded by: _session_lock
        """Allocate n pages, evicting LRU idle sessions if needed (cached
        prefixes are a best-effort optimization; live requests win)."""
        if _engine_fault("engine.page_pressure") is not None:
            # Chaos: behave exactly like a pool with no free pages — the
            # admission fairness/starvation machinery is what's under test.
            self.stats["page_pressure_injected"] += 1
            return None
        pages = self.allocator.alloc(n)
        while pages is None:
            # Pressure ladder (docs/OPERATIONS.md "Agent-aware serving"):
            # unpinned idle sessions first (exactly the pre-pin behavior —
            # with no pins the ladder IS the old LRU loop), then
            # speculative stashes (disposable by contract), then pinned
            # sessions LRU-by-pin-age. Pins never starve admission: a live
            # request always outranks a keep-warm promise.
            unpinned = [s for s in self._sessions if s not in self._pins]
            if unpinned:
                lru_sid = min(unpinned, key=lambda s: self._sessions[s].last_used)
                self.allocator.free(self._sessions.pop(lru_sid).pages)
                self.stats["sessions_evicted"] += 1
            elif self._spec_by_session:
                self._spec_release_locked(next(iter(self._spec_by_session)))
            elif self._pins:
                spill = min(self._pins, key=self._pins.get)  # type: ignore[arg-type]
                self._unpin_session_locked(spill)
                sess = self._sessions.pop(spill, None)
                if sess is not None:
                    self.allocator.free(sess.pages)
                    self.stats["sessions_evicted"] += 1
            else:
                break
            pages = self.allocator.alloc(n)
        return pages

    def _session_hit(self, req: Request) -> tuple[_SessionEntry, int] | None:  # guarded by: _session_lock
        """Returns (entry, reusable-token count) on a prefix-cache hit, without
        mutating the entry — admission may still fail on page starvation and
        must be able to restore the session untouched."""
        if not req.session_id or not self.ecfg.enable_prefix_cache or req.mm_embeds:
            return None
        sess = self._sessions.get(req.session_id)
        if sess is None:
            return None
        cl = len(sess.tokens)
        if 0 < cl < len(req.prompt) and req.prompt[:cl] == sess.tokens:
            return sess, cl
        if 0 < len(req.prompt) <= cl and sess.tokens[: len(req.prompt)] == req.prompt:
            # The prompt is fully resident (exact match or a prefix of the
            # cached history — e.g. a client retry of the same turn). We still
            # need last-token logits to sample, so treat the final prompt
            # token as uncached and re-prefill just that one token (KV
            # rewrite is idempotent); stale KV past the prompt is masked by
            # seq_len.
            return sess, len(req.prompt) - 1
        # Mismatched history (edited conversation, collision): drop the entry.
        self.allocator.free(self._sessions.pop(req.session_id).pages)
        return None

    # ------------------------------------------------------------------
    # agent-aware serving: session keep-warm pins + speculative next-step
    # prefill (docs/OPERATIONS.md "Agent-aware serving"). All state lives
    # under _session_lock next to the sessions it protects; every failure
    # mode below degrades to today's cold path (no pin, full prefill on
    # the follow-up) — never to an error the caller sees.
    # ------------------------------------------------------------------

    def _pin_session_locked(self, sid: str) -> None:  # guarded by: _session_lock
        """Keep-warm pin: exempt the session from gc/LRU until its follow-up
        admits or spec_pin_ttl expires. Over-budget pins spill OLDEST-first
        — the budget, not demand, bounds pinned HBM."""
        budget = max(1, self.ecfg.spec_pin_budget)
        while sid not in self._pins and len(self._pins) >= budget:
            self._unpin_session_locked(min(self._pins, key=self._pins.get))  # type: ignore[arg-type]
        self._pins[sid] = time.time()
        self.stats["session_pins_active"] = len(self._pins)

    def _unpin_session_locked(self, sid: str) -> None:  # guarded by: _session_lock
        """Drop a session's pin AND its speculation state (stashed pages
        freed, in-flight spec jobs cancelled). Idempotent — every terminal
        path may call it."""
        self._pins.pop(sid, None)
        self.stats["session_pins_active"] = len(self._pins)
        self._spec_release_locked(sid)

    def _spec_release_locked(self, sid: str) -> None:  # guarded by: _session_lock
        """Tear down a session's speculative prefills: finished jobs' page
        stashes are freed NOW (forget + free — no lingering refcount-0
        ghosts of wrong guesses), jobs still pending/prefilling cancel
        through the normal request_cancel path next step."""
        st = self._spec_by_session.pop(sid, None)
        if st is None:
            return
        for rid in st["cands"]:
            pages = st["stashes"].pop(rid, None)
            if pages is None:
                self._cancels.add(rid)
            else:
                self._free_spec_stash_locked(pages)
            self.stats["spec_cancelled_total"] += 1

    def _free_spec_stash_locked(self, pages: list[int]) -> None:  # guarded by: _session_lock
        """Free a stashed speculative page chain immediately: sole-holder
        indexed pages drop their mapping first so free() returns them to
        the free list instead of leaving refcount-0 cached entries — pages
        the session (or another stash) still references just decref."""
        for p in pages:
            if self.allocator.is_shared(p) and self.allocator.refcount(p) <= 1:
                self.allocator.forget(p)
        self.allocator.free(pages)

    def _agent_keepwarm_locked(self, sid: str, slot: _Slot) -> None:  # guarded by: _session_lock
        """A step of an agent program finished with expect_followup: pin the
        session, then (for reasoners that declared candidate tool outcomes)
        enqueue one bottom-priority speculative prefill per candidate over
        the just-retained session prefix. The spec.fail fault point vetoes
        speculation (keep-warm only — the degradation every failure shares);
        spec.stall defers the jobs by delay_s (a follow-up that wins the
        race absorbs nothing and the stalled jobs cancel, token-exact)."""
        self._pin_session_locked(sid)
        cands = slot.req.followup_candidates or []
        if not cands or not self._shared_prefix:
            return
        if _engine_fault("spec.fail") is not None:
            # chaos: keep-warm only, the cold-path ladder's first rung
            self.stats["spec_fail_injected"] += 1
            return
        if sid not in self._sessions:
            return  # retention did not happen (e.g. page churn): cold path
        stall = _engine_fault("spec.stall")
        # Speculate over the FULL transcript (slot.tokens = prompt + every
        # generated token): the agent's next prompt resubmits the whole
        # response, while the session entry holds tokens[:-1] (the last
        # token's KV was never written) — the spec job re-prefills that one
        # token plus the candidate, and publishes the chain the follow-up
        # will actually walk.
        st = {
            "parent": slot.req.id,
            "base_len": len(slot.tokens),
            "cands": {},
            "stashes": {},
            "t0": {},
            "tid": (tracing.valid_context(slot.req.trace) or {}).get("trace_id"),
        }
        for j, cand in enumerate(cands[: max(0, self.ecfg.spec_max_candidates)]):
            if not cand:
                continue
            srid = f"{slot.req.id}!spec{j}"
            sreq = Request(
                id=srid,
                prompt=list(slot.tokens) + list(cand),
                sampling=SamplingParams(max_new_tokens=1, temperature=0.0),
                priority=_SPEC_PRIORITY,
                spec_parent=slot.req.id,
            )
            if self._pages_needed(sreq) > self.ecfg.max_pages_per_seq:
                continue  # speculated step would overflow a slot: skip it
            if stall is not None:
                self.stats["spec_stall_injected"] += 1
                self._spec_stalled.append(
                    (time.monotonic() + stall.delay_s, sreq)
                )
            elif not self._spec_submit(sreq):
                continue  # queue saturated: speculation yields, cold path
            st["cands"][srid] = list(cand)
            st["t0"][srid] = (time.time(), time.perf_counter())
            self.stats["spec_started_total"] += 1
        if st["cands"]:
            self._spec_by_session[sid] = st

    def _spec_submit(self, sreq: Request) -> bool:
        """Enqueue an engine-internal speculative job, yielding to real
        traffic: a full pending queue refuses it (False) instead of ever
        consuming a caller's backpressure budget."""
        with self._pending_lock:
            if len(self.pending) >= self.ecfg.max_pending:
                return False
            self._enqueue_locked(sreq)
        return True

    def _drain_spec_stalled(self) -> None:
        """Move stall-faulted speculative jobs whose ready-time passed into
        the pending queue (scheduler thread, top of step). Jobs that cannot
        enqueue yet (queue full) retry next step; jobs cancelled while
        deferred were already filtered out by _drain_cancels."""
        if not self._spec_stalled:
            return
        now = time.monotonic()
        ready = [(rt, r) for rt, r in self._spec_stalled if rt <= now]
        if not ready:
            return
        self._spec_stalled = [(rt, r) for rt, r in self._spec_stalled if rt > now]
        for rt, r in ready:
            if not self._spec_submit(r):
                self._spec_stalled.append((rt, r))

    def _spec_absorb(self, req: Request, start: int) -> None:
        """The real follow-up for a pinned session just left the queue:
        release the pin, settle the speculation — the winner's stash refs
        drop (the follow-up holds its own), losers' pages free immediately,
        still-running jobs cancel. Counters are the triage surface:
        hit/wasted/cancelled (docs/OPERATIONS.md "Agent-aware serving")."""
        sid = req.session_id
        with self._session_lock:
            if sid not in self._pins and sid not in self._spec_by_session:
                return
            self._pins.pop(sid, None)
            self.stats["session_pins_active"] = len(self._pins)
            st = self._spec_by_session.pop(sid, None)
            if st is None:
                return
            suffix = req.prompt[st["base_len"]:]
            winner = None
            for rid, cand in st["cands"].items():
                if (
                    rid in st["stashes"]
                    and len(cand) <= len(suffix)
                    and suffix[: len(cand)] == cand
                ):
                    winner = rid
                    break
            if winner is not None and start > st["base_len"]:
                # The acquisition walk matched past the session prefix:
                # those extra pages ARE the speculated candidate.
                self.stats["spec_hit_total"] += 1
            for rid, cand in st["cands"].items():
                pages = st["stashes"].pop(rid, None)
                if pages is None:
                    self._cancels.add(rid)  # still prefilling: disposable
                    self.stats["spec_cancelled_total"] += 1
                elif rid == winner:
                    self.allocator.free(pages)  # follow-up holds its own refs
                else:
                    self.stats["spec_wasted_tokens_total"] += len(cand)
                    self.stats["spec_cancelled_total"] += 1
                    self._free_spec_stash_locked(pages)

    def _prompt_hashes(self, req: Request) -> list[bytes]:
        """Memoized page-chain hashes of the request's matchable prompt
        prefix (prompt minus its last token): computed once per pending
        request, not once per admission tick."""
        hs = self._req_hashes.get(req.id)
        if hs is None:
            hs = page_chain_hashes(
                req.prompt[: len(req.prompt) - 1], self.ecfg.page_size
            )
            self._req_hashes[req.id] = hs
        return hs

    def _cached_prefix_len(self, req: Request) -> int:
        """Host-side probe (no references taken, nothing mutated): how many
        prompt tokens a session hit or a shared-prefix index hit would skip
        for this request. Drives cache-aware admission ordering."""
        if req.mm_embeds or not self.ecfg.enable_prefix_cache or len(req.prompt) < 2:
            return 0
        with self._session_lock:
            if req.session_id and req.session_id in self._sessions:
                sess = self._sessions[req.session_id]
                cl = len(sess.tokens)
                if 0 < cl < len(req.prompt) and req.prompt[:cl] == sess.tokens:
                    return cl
                if 0 < len(req.prompt) <= cl and sess.tokens[: len(req.prompt)] == req.prompt:
                    return len(req.prompt) - 1
                return 0  # mismatched history: _admit_single drops the entry
            if self._shared_prefix:
                return self.allocator.peek(
                    req.prompt[: len(req.prompt) - 1], hashes=self._prompt_hashes(req)
                )
        return 0

    def _try_admit(self) -> list[TokenEvent]:
        """Admit pending requests. Up to ``prefill_batch`` fresh prompts
        coalesce into ONE padded prefill call (burst TTFT is bounded by
        ceil(burst/N) kernel calls, not the burst size); session-hit,
        shared-prefix-hit and chunked prompts take the single-request path,
        one per tick.

        Cache-aware ordering: before the FIFO scan, the window candidate with
        the LONGEST cached prefix (session or shared-prefix index) admits
        first — its suffix prefill pads to a far smaller bucket than the cold
        prompts' full-length buckets, so hits never queue behind cold
        prefills. Fresh candidates that share their leading page with a
        batch-mate admitted THIS tick are deferred one tick
        (``prefix_batch_deferrals``): next tick they hit the published prefix
        instead of redundantly re-prefilling it.

        Priority (overload control): the pending queue is kept priority-
        tier-ordered at enqueue (``_enqueue_locked``), so this scan tries
        higher tiers first without any reordering of its own — all-default
        traffic behaves bit-identically to the pre-priority scheduler.

        Fairness: a page-starved request does not block the queue — admission
        scans up to ``admit_window`` entries past it (bounded reorder). The
        head — the oldest top-tier request — is always tried first, so freed
        pages reach it before anyone behind it; if later requests keep
        admitting around a starved head for ``head_starve_fifo_ticks``
        consecutive ticks, the window collapses to strict FIFO until the
        head admits. Cache-hit hoisting (within the top priority tier
        present) ages the same fence whenever it bypasses the head."""
        if not self.pending:
            return []
        avail = self._slots_available()  # free slots minus prefill-job
        # reservations (mixed scheduling): a completing job must always find
        # a slot, so classic admission never claims the reserved count
        if avail <= 0:
            return []
        N = min(max(1, self.ecfg.prefill_batch), avail)
        window = max(1, self.ecfg.admit_window)
        if self._head_starved_ticks >= self.ecfg.head_starve_fifo_ticks:
            window = 1  # anti-starvation fence: freed pages go to the head
        with self._pending_lock:
            # Snapshot window + batch room: admissions never consume window
            # positions (only SKIPS do — matching the old in-place scan,
            # where removals shifted the deque under a fixed skip bound), so
            # a burst tick still admits up to N while reorder depth stays
            # bounded by `window`.
            cands = [
                self.pending[i] for i in range(min(window + N, len(self.pending)))
            ]
        # The pending queue is priority-tier-ordered at enqueue
        # (_enqueue_locked), so a plain positional scan IS the priority
        # scan: the head is the oldest top-tier request, and every fairness
        # and fence rule below behaves exactly as in the flat-priority
        # scheduler. Cache-hit hoisting stays within the top tier present —
        # a cached lower-tier prompt must not jump a higher tier.
        head = cands[0]
        top_priority = head.priority
        best = None  # (cached_len, window index, req) — top priority tier only
        for i in range(min(window, len(cands))):
            if cands[i].priority != top_priority:
                break  # tiers are contiguous: nothing below is top-tier
            cl = self._cached_prefix_len(cands[i])
            if cl > 0 and (best is None or cl > best[0]):
                best = (cl, i, cands[i])
        if best is not None:
            _, i, req = best
            free_slot = next(j for j, s in enumerate(self.slots) if s is None)
            single = self._admit_single(req, free_slot)
            if single:
                if i > 0:
                    self.stats["admission_reorders"] += 1
                    # bypassing the head ages the anti-starvation fence
                    self._head_starved_ticks += 1
                else:
                    self._head_starved_ticks = 0
                return single
            # starved even with its cached pages: fall through to the
            # priority scan, which skips it like any starved single
        batch: list[tuple[Request, int, list[int]]] = []  # (req, slot, pages)
        batch_chains: set[bytes] = set()  # leading-page chain hashes in `batch`
        claimed: set[int] = set()
        head_starved = False
        skipped_starved = False
        skips = 0
        for req in cands:
            if len(batch) >= N or skips >= window:
                break
            free_slot = next(
                (j for j, s in enumerate(self.slots) if s is None and j not in claimed),
                None,
            )
            if free_slot is None:
                break
            chunked = (
                self.ecfg.prefill_chunk is not None
                and len(req.prompt) > self.ecfg.prefill_chunk
            )
            # Branched requests take the single path: the fork needs the
            # last-prompt-token logits, which the batched prefill's padded
            # multi-row form does not keep per-request. Handoff phases do
            # too: export needs those logits, adoption installs live with
            # no prefill — both are _admit_single features.
            chunked = (
                chunked
                or req.n_branches > 1
                or req.handoff is not None
                or req.handoff_export
            )
            with self._session_lock:
                # one hold covers both probes: the has_sess membership test
                # races gc_sessions/free_session on other threads otherwise
                has_sess = (
                    req.session_id is not None
                    and self.ecfg.enable_prefix_cache
                    and req.session_id in self._sessions
                )
                index_hit = False
                if not (chunked or has_sess or req.mm_embeds) and self._shared_prefix:
                    index_hit = (
                        self.allocator.peek(
                            req.prompt[: len(req.prompt) - 1],
                            hashes=self._prompt_hashes(req),
                        )
                        > 0
                    )
            if chunked or has_sess or req.mm_embeds or index_hit:
                if batch:
                    break  # flush the fresh batch first; single path next tick
                single = self._admit_single(req, free_slot)
                if single:
                    if skipped_starved:
                        self.stats["admission_reorders"] += 1
                    if req is head:
                        self._head_starved_ticks = 0
                    elif head_starved:
                        # a single-path admission bypassed the starved head:
                        # it must age the fence like batch bypasses do
                        self._head_starved_ticks += 1
                    return single
                # page-starved single: scan past it
                skipped_starved = True
                head_starved = head_starved or req is head
                skips += 1
                continue
            h1 = None
            if self._shared_prefix and len(req.prompt) > self.ecfg.page_size:
                h1 = self._prompt_hashes(req)[0]
                if h1 in batch_chains:
                    # a batch-mate admitted THIS tick is about to prefill (and
                    # publish) this same leading page: defer one tick so this
                    # request reuses it instead of re-prefilling the prefix
                    self.stats["prefix_batch_deferrals"] += 1
                    skips += 1
                    continue
            with self._session_lock:
                pages = self._alloc_with_eviction(self._pages_needed(req))
            if pages is None:
                # page-starved: scan past it (decode will free pages)
                skipped_starved = True
                head_starved = head_starved or req is head
                skips += 1
                continue
            if h1 is not None:
                batch_chains.add(h1)
                self.stats["prefix_index_misses"] += 1
            with self._pending_lock:
                self.pending.remove(req)
            self._req_hashes.pop(req.id, None)
            st = self._submit_t.get(req.id)
            if st is not None:
                self.latency.observe("queue_wait_ms", (time.monotonic() - st) * 1e3)
            self._tr_dequeue(req)
            claimed.add(free_slot)
            batch.append((req, free_slot, pages))
        if head_starved and batch:
            self.stats["admission_reorders"] += 1
        if head_starved and self.pending and self.pending[0] is head:
            self._head_starved_ticks += 1
        else:
            self._head_starved_ticks = 0
        if not batch:
            return []
        if len(batch) == 1:
            req, slot_idx, pages = batch[0]
            row = build_page_table(pages, self.ecfg.max_pages_per_seq)
            last_logits = self._prefill(req.prompt, 0, row)
            self.stats["prefill_tokens"] += len(req.prompt)
            return self._sample_first_and_install(req, slot_idx, pages, row, last_logits)
        return self._admit_batch(batch)

    # afcheck: owns-pages each row's pages install into its slot (release/preempt free them)
    def _admit_batch(self, batch: list[tuple[Request, int, list[int]]]) -> list[TokenEvent]:
        """One padded multi-row prefill for ≥2 fresh requests, then one
        vectorized first-token sample across all rows."""
        N = self.ecfg.prefill_batch
        maxp = self.ecfg.max_pages_per_seq
        bucket = self.ecfg.prefill_bucket(max(len(r.prompt) for r, _, _ in batch))
        tokens = np.zeros((N, bucket), np.int32)
        lengths = np.zeros((N,), np.int32)
        rows = np.zeros((N, maxp), np.int32)
        temps = np.zeros((N,), np.float32)
        top_ks = np.zeros((N,), np.int32)
        top_ps = np.ones((N,), np.float32)
        row_tables = []
        for j, (req, _, pages) in enumerate(batch):
            row = build_page_table(pages, maxp)
            row_tables.append(row)
            tokens[j, : len(req.prompt)] = np.asarray(req.prompt, np.int32)
            lengths[j] = len(req.prompt)
            rows[j] = row
            s = req.sampling
            temps[j], top_ks[j], top_ps[j] = s.temperature, s.top_k, s.top_p
        fn = _batch_prefill_fn(self.prefill_cfg, self.ecfg, bucket, self.mesh)
        last, self.cache.k_pages, self.cache.v_pages = fn(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(tokens),
            jnp.asarray(lengths),
            jnp.asarray(rows),
        )
        self._draft_replay(
            _batch_prefill_fn, bucket,
            jnp.asarray(tokens), jnp.asarray(lengths), jnp.asarray(rows),
        )
        masks = None
        for j, (req, _, _) in enumerate(batch):
            m = self._first_token_mask(req)
            if m is not None:
                if masks is None:
                    masks = np.ones((N, self.cfg.vocab_size), bool)
                masks[j] = m[0]
        sample_from = jnp.where(jnp.asarray(masks), last, _MASKED) if masks is not None else last
        toks = sample_tokens(
            sample_from,
            self._next_rng(),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
        )
        lps = jnp.take_along_axis(
            jax.nn.log_softmax(last, axis=-1), toks[:, None], axis=1
        )[:, 0]
        toks_np, lps_np = np.asarray(toks), np.asarray(lps)
        self.stats["prefill_tokens"] += int(lengths.sum())
        self.stats["prefill_batches"] += 1
        with self._telemetry_lock:
            self._tick_tokens.append(int(lengths.sum()))
        return [
            self._install(req, slot_idx, pages, row_tables[j], int(toks_np[j]), float(lps_np[j]))
            for j, (req, slot_idx, pages) in enumerate(batch)
        ]

    def _acquire_pages_locked(
        self, req: Request
    ) -> tuple[list[int], int, str] | None:
        """Tracing shim over :meth:`_acquire_pages_impl`: host/peer KV
        restores happen inside the acquisition's lookup walk (batched H2D
        upload), so a counter delta across the call is the exact "this
        admission paid a tier restore" signal — recorded as an
        ``engine.kv_restore`` span for traced requests, zero extra work
        for the rest."""
        e = self._traces.get(req.id)
        if e is None:
            return self._acquire_pages_impl(req)
        r0 = self.stats.get("kv_offload_restored", 0)
        t0_w, t0_m = time.time(), time.perf_counter()
        acq = self._acquire_pages_impl(req)
        restored = self.stats.get("kv_offload_restored", 0) - r0
        if restored and acq is not None:
            self._tracer.record_span(
                "engine.kv_restore", e["tid"], t0_w,
                (time.perf_counter() - t0_m) * 1e3, {"pages": restored},
            )
        return acq

    def _acquire_pages_impl(
        self, req: Request
    ) -> tuple[list[int], int, str] | None:
        """Page acquisition for ONE request (caller holds the session lock):
        session prefix hit (with copy-on-write privatization), cross-request
        shared-prefix lookup, or fresh allocation. Returns ``(pages, start,
        kind)`` with ``kind`` in {"session", "index", "fresh"} and ``start``
        the cached-prefix length prefill skips, or None on page starvation
        (all acquisition state restored; the caller retries a later tick).
        Shared by classic single-request admission and mixed-scheduling
        prefill-job creation (docs/MIXED_SCHEDULING.md), so the two paths
        cannot drift on cache/COW semantics."""
        ps = self.ecfg.page_size
        index_hit = False
        with self._session_lock:  # RLock: callers may already hold it
            hit = self._session_hit(req)
            if (
                hit is not None
                and self.ecfg.spec_prefill
                and self._shared_prefix
                and not req.mm_embeds
                and len(req.prompt) > 1
                and self.allocator.peek(
                    req.prompt[: len(req.prompt) - 1],
                    hashes=self._prompt_hashes(req),
                )
                > hit[1]
            ):
                # Agent-aware serving: the shared index holds MORE of this
                # prompt than the session entry — a speculative next-step
                # prefill published the follow-up's tokens while the tool
                # ran. Ride the index walk instead (the absorb); the
                # session entry stays put and its refs release normally
                # when this request finishes and re-retains the session.
                # Gated on spec_prefill: knob-off acquisition is
                # bit-compatible with today's.
                hit = None
            total_pages = self._pages_needed(req)

            if hit is not None:
                sess, start = hit
                # Claim the session FIRST: the eviction loop below must never
                # be able to free the very pages we are about to reuse.
                self._sessions.pop(req.session_id, None)
                extra_needed = total_pages - len(sess.pages)
                extra = self._alloc_with_eviction(extra_needed) if extra_needed > 0 else []
                if extra is None:
                    self._sessions[req.session_id] = sess  # restore; retry later
                    return None  # page-starved; decode will free pages
                pages = sess.pages + extra
                # Copy-on-write: this request will WRITE every page from
                # start//ps onward (suffix re-prefill from `start`, then
                # decode past the prompt). Indexed pages are immutable and
                # pages other holders reference must not be touched, so any
                # shared page in the write range is privatized first. This
                # bites on the full-prompt retry path (start=len(prompt)-1):
                # the session's published pages BEYOND the retried prompt
                # would otherwise be silently corrupted under the index's
                # feet. Sole-holder indexed pages just drop their (about to
                # be stale) index mapping and are written in place; pages
                # other requests hold get a fresh copy.
                widx0 = start // ps
                pages = list(pages)
                cow_idx = []
                # Write range ends at the request's own page budget: a retry
                # shorter than the session history never touches the
                # history's tail pages, so those keep their index entries
                # (and other holders) untouched.
                for k in range(widx0, min(len(pages), total_pages)):
                    if not self.allocator.is_shared(pages[k]):
                        continue
                    if self.allocator.refcount(pages[k]) <= 1:
                        self.allocator.forget(pages[k])
                        self.stats["prefix_pages_unpublished"] += 1
                    else:
                        cow_idx.append(k)
                if cow_idx:
                    fresh = self._alloc_with_eviction(len(cow_idx))
                    if fresh is None:
                        if extra:
                            self.allocator.free(extra)
                        self._sessions[req.session_id] = sess
                        return None  # page-starved; retry later
                    for k, new_page in zip(cow_idx, fresh):
                        if k == widx0 and start % ps:
                            # the only page whose prior slots (< start) this
                            # request still READS; later pages are fully
                            # rewritten before any read touches them
                            self._copy_page(pages[k], new_page)
                        self.allocator.free([pages[k]])  # drop this holder's ref
                        pages[k] = new_page
                    self.stats["prefix_cow_copies"] += len(cow_idx)
                if len(pages) > total_pages:
                    # A retry shorter than the session history: drop the tail
                    # beyond this request's own page budget. The slot's table
                    # must not reference pages it may never legally write —
                    # a pipelined decode span's stale post-finish write would
                    # otherwise land on them (indexed tail pages stay cached
                    # and matchable; the rest return to the free list). Past
                    # the shortened table, such writes hit garbage page 0,
                    # the designed sink.
                    self.allocator.free(pages[total_pages:])
                    pages = pages[:total_pages]
            else:
                matched: list[int] = []
                start = 0
                if self._shared_prefix and not req.mm_embeds and len(req.prompt) > 1:
                    # Cross-request reuse: longest content-addressed full-page
                    # prefix of the prompt (minus the last token — its logits
                    # must be computed to sample). Matched pages are incref'd.
                    matched, start = self.allocator.lookup(
                        req.prompt[: len(req.prompt) - 1],
                        hashes=self._prompt_hashes(req),
                    )
                if matched:
                    extra_needed = total_pages - len(matched)
                    extra = self._alloc_with_eviction(extra_needed) if extra_needed > 0 else []
                    if extra is None:
                        self.allocator.free(matched)  # drop refs; retry later
                        return None
                    pages = matched + extra
                    index_hit = True
                else:
                    pages = self._alloc_with_eviction(total_pages)
                    if pages is None:
                        return None
                    if self._shared_prefix and len(req.prompt) > ps:
                        self.stats["prefix_index_misses"] += 1
        kind = "session" if hit is not None else ("index" if index_hit else "fresh")
        return pages, start, kind

    def _dequeue_acquired(self, req: Request, kind: str, start: int) -> None:
        """Post-acquisition bookkeeping shared by the classic single path and
        mixed job creation: the request leaves the pending queue (by
        identity — the fairness window may admit from behind a page-starved
        head) and its cache hit, if any, is counted."""
        with self._pending_lock:
            self.pending.remove(req)
        self._req_hashes.pop(req.id, None)
        st = self._submit_t.get(req.id)
        if st is not None:
            self.latency.observe("queue_wait_ms", (time.monotonic() - st) * 1e3)
        self._tr_dequeue(req, start)
        if kind == "session":
            self.stats["prefix_cache_hits"] += 1
            self.stats["prefix_tokens_reused"] += start
        elif kind == "index":
            self.stats["prefix_index_hits"] += 1
            self.stats["prefix_tokens_reused"] += start
        if req.resumed_from > 0 and kind != "fresh" and start > 0:
            # A preempted request re-admitting over cached pages: the
            # preempt/resume cycle rode the prefix index instead of paying a
            # full re-prefill (docs/FAULT_TOLERANCE.md overload control).
            self.stats["resume_prefix_hits_total"] += 1
        if (
            self.ecfg.spec_prefill
            and req.session_id
            and req.spec_parent is None
        ):
            # Agent-aware serving: a follow-up on a pinned session settles
            # the pin + any speculative prefills (hit/waste accounting,
            # loser pages freed). One dict check for unpinned sessions.
            self._spec_absorb(req, start)

    def _admit_single(self, req: Request, free_slot: int) -> list[TokenEvent]:
        """Single-request admission: session prefix-cache reuse, cross-request
        shared-prefix reuse (both suffix-only prefill) and chunked long
        prompts flow through here."""
        acq = self._acquire_pages_locked(req)
        if acq is None:
            return []  # page-starved; decode will free pages
        pages, start, kind = acq
        if req.handoff is not None:
            live = self._try_handoff_install(req, free_slot, pages, start, kind)
            if live is not None:
                return live
            # Shortfall (walk fell short, tail aged out, upload failed):
            # fall through to the ordinary suffix prefill below, which
            # re-samples the same first token under greedy — token-exact.
            self.stats["kv_handoff_failed_total"] += 1
        self._dequeue_acquired(req, kind, start)
        row = build_page_table(pages, self.ecfg.max_pages_per_seq)
        if req.mm_embeds:
            # Whole-prompt injection prefill (chunking doesn't apply: the
            # inject buffer is positioned against the full prompt).
            last_logits = self._prefill_mm(req.prompt, row, req.mm_embeds)
        else:
            last_logits = self._prefill(req.prompt[start:], start, row)
        self.stats["prefill_tokens"] += len(req.prompt) - start
        with self._telemetry_lock:
            self._tick_tokens.append(len(req.prompt) - start)
        return self._sample_first_and_install(req, free_slot, pages, row, last_logits)

    # afcheck: owns-pages installs into the slot table (and forks siblings onto shared pages)
    def _sample_first_and_install(
        self, req: Request, slot_idx: int, pages: list[int], row: np.ndarray, last_logits
    ) -> list[TokenEvent]:
        s = req.sampling
        masked = self._first_token_mask(req)
        sample_from = (
            jnp.where(jnp.asarray(masked[0]), last_logits, _MASKED)
            if masked is not None
            else last_logits
        )
        tok_arr = sample_tokens(
            sample_from[None],
            self._next_rng(),
            jnp.asarray([s.temperature], jnp.float32),
            jnp.asarray([s.top_k], jnp.int32),
            jnp.asarray([s.top_p], jnp.float32),
        )
        tok = int(tok_arr[0])
        first_logprob = float(jax.nn.log_softmax(last_logits)[tok])
        if req.handoff_export:
            ev = self._try_handoff_export(req, pages, tok, first_logprob)
            if ev is not None:
                return [ev]
            # Export declined (ineligible request, injected fault, D2H
            # failure): decode locally — single-node prefill+decode on the
            # would-be prefill node is the degradation contract.
            self.stats["kv_handoff_failed_total"] += 1
            self.stats["kv_handoff_fail_export_total"] += 1
        if req.n_branches <= 1:
            return [self._install(req, slot_idx, pages, row, tok, first_logprob)]
        # Branch fork (docs/PREFIX_CACHING.md "Fork / COW branches").
        # Ordering matters twice: branch 0 sampled FIRST (above) so its RNG
        # position — and therefore its tokens under greedy AND sampling —
        # is bit-identical to the unforked request; siblings fork BEFORE
        # branch 0 installs, while admission still owns `pages`, so a
        # branch 0 that finishes on its first token (stop id) cannot free
        # the prompt pages out from under the incref.
        sibling_events = self._fork_at_install(req, slot_idx, pages, last_logits)
        ev0 = self._install(req, slot_idx, pages, row, tok, first_logprob)
        return [ev0] + sibling_events

    def _fork_at_install(
        self, req: Request, parent_slot: int, parent_pages: list[int], last_logits
    ) -> list[TokenEvent]:
        """Fork ``req.n_branches - 1`` sibling branches off a just-prefilled
        prompt: each shares the prompt's FULL pages copy-on-write (incref —
        no re-prefill, no H2D), privately copies the partial tail page
        (decode writes land there), samples its first token from the same
        last-prompt-token logits under its own RNG stream, and installs as
        an ordinary decode batch-mate. A sibling that finds no free slot or
        pages degrades to the pending queue instead (``senior=True`` so it
        re-admits next — through the prefix index branch 0's install is
        about to publish, paying only the tail-suffix re-prefill)."""
        ps = self.ecfg.page_size
        L = len(req.prompt)
        full = L // ps
        total = self._pages_needed(req)
        lsm = None  # log-softmax of the prompt logits, computed once
        events: list[TokenEvent] = []
        s = req.sampling
        with self._pending_lock:
            # Every branch shares the parent's submit-time deadline window
            # (the parent's expiry was registered at submit()).
            parent_exp = self._deadline_at.get(req.id)
        for j in range(1, req.n_branches):
            sub = dataclasses.replace(
                req, id=branch_rid(req.id, j), n_branches=1, session_id=None
            )
            slot_idx = next(
                (
                    i
                    for i, sl in enumerate(self.slots)
                    if sl is None and i != parent_slot
                ),
                None,
            )
            pages_j = fresh = None
            if slot_idx is not None and self._slots_available() > 1:
                # > 1: this fork must not consume the last slot a mixed
                # prefill job reserved (branch 0's own slot was already
                # claimed by admission before jobs could reserve it).
                with self._session_lock:
                    fresh = self._alloc_with_eviction(total - full)
                    if fresh is not None:
                        self.allocator.incref(parent_pages[:full])
                        pages_j = parent_pages[:full] + fresh
            if pages_j is None:
                # Degraded fork: no slot/pages right now — re-admit through
                # the queue. Correct (the published prompt prefix makes it
                # an index hit), just not free; the counter is the operator
                # signal that fan-out exceeds capacity.
                with self._pending_lock:
                    self._enqueue_locked(sub, senior=True)
                    if parent_exp is not None:
                        self._deadline_at[sub.id] = parent_exp
                self.stats["branch_forks_degraded_total"] += 1
                self._tr_fork(req.id, sub.id, degraded=True)
                continue
            if L % ps:
                # The only page whose prompt KV the sibling still READS but
                # whose remaining slots its decode will WRITE: private copy.
                self._copy_page(parent_pages[full], fresh[0])
            row_j = build_page_table(pages_j, self.ecfg.max_pages_per_seq)
            tok_arr = sample_tokens(
                last_logits[None],
                self._next_rng(),  # distinct per-branch RNG stream
                jnp.asarray([s.temperature], jnp.float32),
                jnp.asarray([s.top_k], jnp.int32),
                jnp.asarray([s.top_p], jnp.float32),
            )
            tok_j = int(tok_arr[0])
            if lsm is None:
                lsm = jax.nn.log_softmax(last_logits)
            if parent_exp is not None:
                with self._pending_lock:
                    self._deadline_at[sub.id] = parent_exp
            self._tr_fork(req.id, sub.id)
            events.append(
                self._install(sub, slot_idx, pages_j, row_j, tok_j, float(lsm[tok_j]))
            )
            self.stats["branch_forks_total"] += 1
        return events

    def _copy_page(self, src: int, dst: int) -> None:
        """Copy-on-write: duplicate page `src` into `dst` (all layers), on the
        target cache and — when speculation is on — the draft cache, so the
        draft's view of a privatized page stays in sync."""
        fn = _copy_page_fn()
        self.cache.k_pages, self.cache.v_pages = fn(
            self.cache.k_pages, self.cache.v_pages, jnp.int32(src), jnp.int32(dst)
        )
        if self.draft_cache is not None:
            self.draft_cache.k_pages, self.draft_cache.v_pages = fn(
                self.draft_cache.k_pages, self.draft_cache.v_pages,
                jnp.int32(src), jnp.int32(dst),
            )

    def _capture_page_kv(self, page: int):
        """Demote capture (pool callback; scheduler/event-loop thread under
        _session_lock): lazy device slices of one page's K/V. Slicing
        dispatches NEW device buffers whose content is the page AT CAPTURE
        TIME — later donating decode/prefill dispatches recycle the parent
        pool buffer, never these — so the offload worker can run the
        device→host transfer (_fetch_page_kv) off-thread without racing the
        tick path. Target cache only: a restored page's DRAFT-cache twin
        stays stale, which can only lower speculative acceptance (the
        verify forward reads the target cache — emitted tokens are exact)."""
        sl = lambda a: a[:, page]  # noqa: E731
        for _ in range(1000):
            try:
                return (
                    jax.tree.map(sl, self.cache.k_pages),
                    jax.tree.map(sl, self.cache.v_pages),
                )
            except RuntimeError:
                # Lost the donation race: a concurrent donating dispatch on
                # the worker thread deleted the pool buffer between our
                # attribute read and the slice (the worker reassigns the new
                # buffers WITHOUT _session_lock — so waiting here cannot
                # deadlock). Captured pages are immutable (published
                # prefixes, refcount-0 cached, released handoff tails), so a
                # post-tick recapture is bit-identical. Seen at scale on the
                # kv_fetch export path, which captures from the event-loop
                # thread while ticks run. The ~1s budget covers backends
                # whose dispatch is SYNCHRONOUS (CPU): there the buffer
                # stays deleted for the whole prefill step, hundreds of ms
                # for long prompts, not the microseconds an async TPU
                # dispatch leaves between delete and reassign.
                time.sleep(0.001)
        raise RuntimeError(f"page {page} capture kept losing the donation race")

    def _upload_page_kv(self, payloads, pages: list[int]) -> None:
        """Restore host-tier payloads into HBM `pages` (pool callback;
        admission path under _session_lock) — ONE jitted scatter for the
        whole batch, leaf-by-leaf over the (possibly quantized) pool
        pytree. The round-tripped bytes are bit-identical — scales
        included — so attention over restored pages is token-exact within
        the active kv_quant_dtype."""
        stack = lambda *xs: jnp.asarray(np.stack(xs, axis=1))  # noqa: E731  [L, N, ...]
        k_host = jax.tree.map(stack, *[p[0] for p in payloads])
        v_host = jax.tree.map(stack, *[p[1] for p in payloads])
        fn = _restore_page_fn()
        self.cache.k_pages, self.cache.v_pages = fn(
            self.cache.k_pages, self.cache.v_pages,
            k_host, v_host,
            jnp.asarray(np.asarray(pages, np.int32)),
        )

    def close(self) -> None:
        """Release background resources (the KV offload worker). Idempotent;
        the engine stays steppable afterwards — demotion simply stops."""
        # close() only joins the worker thread (no pool bookkeeping is
        # touched) and MUST NOT hold _session_lock — the worker needs the
        # lock to commit its in-flight item before it can exit.
        self.allocator.close()  # afcheck: ignore[guarded-by] thread join only; holding the lock would deadlock the worker's final commit

    def scheduler_stats(self) -> dict[str, float]:
        """Scheduler-latency gauges (docs/MIXED_SCHEDULING.md): inter-token
        arrival percentiles over a rolling window (the stall the mixed tick
        bounds) and tokens carried per device dispatch. Exported on /stats,
        heartbeats, and re-exported by the control plane as per-node
        Prometheus gauges (metrics.export_engine_stats)."""
        with self._telemetry_lock:
            w = sorted(self._itl_window)
            tt = list(self._tick_tokens)

        def pct(p: float) -> float:
            return w[min(len(w) - 1, int(len(w) * p))] * 1e3 if w else 0.0

        return {
            "itl_ms_p50": round(pct(0.50), 3),
            "itl_ms_p99": round(pct(0.99), 3),
            "tokens_per_tick": round(sum(tt) / len(tt), 2) if tt else 0.0,
        }

    def prefix_cache_stats(self) -> dict[str, int]:
        """Gauges for the shared-prefix page pool (counters live in
        ``self.stats``); exported via heartbeats, /stats and /metrics."""
        with self._session_lock:
            a = self.allocator
            return {
                "prefix_cached_pages": a.cached_pages,
                "prefix_shared_pages": a.shared_pages,
                "cached_sessions": len(self._sessions),
                # Tiered KV: demoted entries resident in the host store
                # (counters kv_offload_{demoted,restored,restore_fail} live
                # in self.stats; this is the matching occupancy gauge).
                "kv_offload_host_pages": a.host_pages,
            }

    # -- cluster tier (docs/PREFIX_CACHING.md "Cluster tier") ----------

    def prefix_sketch(self) -> dict | None:
        """Compact prefix-index summary for heartbeat publication: truncated
        chain-hash digests the gateway's affinity router scores dispatch
        candidates with. None when the shared-prefix index is off or
        ``prefix_sketch_bytes`` is 0 (the node then never attracts
        affinity traffic)."""
        if not self._shared_prefix or self.ecfg.prefix_sketch_bytes <= 0:
            return None
        with self._session_lock:
            return self.allocator.sketch(self.ecfg.prefix_sketch_bytes)

    def peek_prefix(self, tokens: Sequence[int]) -> int:
        """Length (tokens) of the longest locally indexed full-page prefix
        of `tokens` — both tiers; no references taken. The peer-prefetch
        path asks this before fetching, so only the MISSING page range goes
        over the wire."""
        if not self._shared_prefix:
            return 0
        with self._session_lock:
            return self.allocator.peek(tokens)

    def page_payload_spec(self) -> list[tuple[str, tuple[int, ...]]]:
        """``(dtype, shape)`` per flattened leaf of ONE exported page
        payload — the wire contract for cross-node kv transfer
        (model_node.kv_export_pages / maybe_prefetch_kv). Plain pools have
        two leaves (k, v); quantized pools four (k values, k scales, v
        values, v scales) — the scales ride the wire so an adopted page
        dequantizes identically on the far side."""
        leaves = jax.tree.leaves((self.cache.k_pages, self.cache.v_pages))
        return [(str(a.dtype), (a.shape[0],) + a.shape[2:]) for a in leaves]

    def build_page_payload(self, leaves: Sequence[Any]):
        """Rebuild one host-store payload from its flattened wire leaves
        (inverse of flattening a captured page — same treedef as the
        pool)."""
        treedef = jax.tree.structure((self.cache.k_pages, self.cache.v_pages))
        return jax.tree.unflatten(treedef, list(leaves))

    def adopt_kv_pages(
        self, entries: Sequence[tuple[bytes, int, tuple[int, ...], Any]]
    ) -> int:
        """Install peer-fetched page payloads ``(chain, depth, tokens,
        (k, v) numpy arrays)`` into the pool's host store; they restore
        through the ordinary lookup walk at the next admission (batched H2D
        upload, restore-failure → shorter prefix → re-prefill, token-exact
        under greedy). Returns the number adopted."""
        if not self._shared_prefix:
            return 0
        with self._session_lock:
            return self.allocator.adopt_host_pages(entries)

    def export_kv_pages(
        self, chains: Sequence[bytes], max_pages: int = 64
    ) -> list[tuple[bytes, int, Any]]:
        """Serve a peer's ``kv_fetch``: for each requested chain hash that
        is locally indexed, ``(chain, depth, (k, v) numpy payload)``.
        Two-phase like demotion — page capture under the session lock
        (content fixed at capture), the blocking device→host copy OUTSIDE
        it, so serving a peer never stalls this node's tick path."""
        if not self._shared_prefix:
            return []
        with self._session_lock:
            prepped = self.allocator.export_prep(
                list(chains)[: max(0, int(max_pages))], self._capture_page_kv
            )
        out: list[tuple[bytes, int, Any]] = []
        for chain, depth, obj, kind in prepped:
            if kind == "host":
                out.append((chain, depth, obj))
            else:
                try:
                    out.append((chain, depth, _fetch_page_kv(obj)))
                except Exception:  # afcheck: ignore[except-swallow] best-effort peer serving: a failed D2H copy shortens the response and the requester re-prefills
                    continue
        return out

    # ------------------------------------------------------------------
    # Live-slot handoff (disaggregated prefill/decode pools,
    # docs/ARCHITECTURE.md "Two-phase dispatch"): the full prompt pages
    # move through the ordinary publish→kv_fetch→adopt path above; what
    # ships HERE is the piece that path cannot carry — the partial tail
    # page (lookup never matches a page holding the last prompt token)
    # plus the sampler state (first token + its logprob), so the decode
    # node resumes the exact slot the prefill node would have decoded.

    def _gc_handoffs_locked(self) -> None:
        """Expire + bound both handoff stashes (caller holds _session_lock).
        Oldest-first eviction under the cap: a stuck decode pool sheds its
        stalest exports, and every shed is just a future re-prefill."""
        now = time.monotonic()
        for stash in (self._handoff_out, self._handoff_in):
            for key in [k for k, v in stash.items() if v[0] < now]:
                del stash[key]
            while len(stash) >= _HANDOFF_STASH_MAX:
                del stash[next(iter(stash))]

    def pop_handoff_desc(self, request_id: str) -> dict | None:
        """The phase-1 result attachment: the descriptor for a request that
        just finished with ``finish_reason="handoff"``. The stash entry
        (and its tail payload) stays resident for the decode node's fetch —
        only ``export_handoff_tail`` or the TTL removes it."""
        with self._session_lock:
            entry = self._handoff_out.get(request_id)
        return dict(entry[1]) if entry is not None else None

    def export_handoff_tail(self, handoff_id: str) -> tuple[dict, Any] | None:
        """Serve the decode node's tail fetch: pop the stashed (descriptor,
        host payload) for one handoff id, or None if it aged out / never
        exported. One-shot — the protocol fetches exactly once, and a
        popped entry cannot keep pinning its host page copy."""
        with self._session_lock:
            self._gc_handoffs_locked()
            entry = self._handoff_out.pop(handoff_id, None)
        if entry is None:
            return None
        return entry[1], entry[2]

    def adopt_handoff_tail(self, handoff_id: str, payload: Any) -> bool:
        """Stash a fetched tail payload for its phase-2 admission. The
        caller (model_node.maybe_prefetch_kv) already validated the wire
        leaves against ``page_payload_spec`` and rebuilt the pool pytree
        via ``build_page_payload`` — mixed-dtype fleets fail validation
        there and degrade to a re-prefill."""
        if not self._shared_prefix:
            return False
        with self._session_lock:
            self._gc_handoffs_locked()
            self._handoff_in[handoff_id] = (
                time.monotonic() + _HANDOFF_TTL_S,
                payload,
            )
        return True

    def _try_handoff_export(
        self, req: Request, pages: list[int], tok: int, first_logprob: float
    ) -> TokenEvent | None:
        """Phase 1 of a two-phase dispatch: instead of installing the slot,
        publish the prompt's full pages into the prefix index (the decode
        node pulls them over the ordinary kv_fetch path), capture + stash
        the partial tail page with the sampled first token, release every
        page ref, and emit ONE terminal event (finish_reason="handoff").
        Returns None to DECLINE — ineligible request, injected fault, or a
        failed D2H copy — in which case the caller installs normally and
        this node decodes the request itself, the degradation contract
        every handoff failure mode shares."""
        s = req.sampling
        if (
            not self._shared_prefix
            or req.grammar is not None
            or req.mm_embeds
            or req.n_branches > 1
            or len(req.prompt) < 2
            # a preempted-and-resumed incarnation already decoded locally:
            # exporting now would hand off mid-generation state the
            # phase-2 request (the ORIGINAL prompt) cannot validate
            or req.resumed_from > 0
            # first token already terminal: there is nothing to hand off
            or tok in s.stop_token_ids
            or s.max_new_tokens <= 1
        ):
            return None
        # afcheck: caller-error every decline is counted at the call site (kv_handoff_failed_total, kv_handoff_fail_export_total)
        if _engine_fault("kv.handoff_fail") is not None:
            return None
        ps = self.ecfg.page_size
        L = len(req.prompt)
        k = (L - 1) // ps  # tail page: positions [k*ps, L)
        t0_w, t0_m = time.time(), time.perf_counter()
        with self._session_lock:
            handle = self._capture_page_kv(pages[k])
        try:
            payload = _fetch_page_kv(handle)
        except Exception:  # afcheck: caller-error decline counted at the call site (kv_handoff_fail_export_total)
            return None  # decline: decode locally, pages still owned
        desc = {
            "id": req.id,
            "t0": tok,
            "logprob": first_logprob,
            "prompt_tokens": L,
            "pages": k,
            "page_size": ps,
        }
        with self._session_lock:
            # Same disposition as _release's non-session path: published
            # full pages survive the free as refcount-0 cached index
            # entries; the tail + growth pages return to the free list.
            self.allocator.publish(req.prompt, pages)
            self.allocator.free(pages)
            self._gc_handoffs_locked()
            self._handoff_out[req.id] = (
                time.monotonic() + _HANDOFF_TTL_S,
                desc,
                payload,
            )
        self.stats["kv_handoff_initiated_total"] += 1
        st = self._submit_t.pop(req.id, None)
        if st is not None:
            # phase-1 TTFT: submit → the first token the handoff carries
            self.latency.observe("ttft_ms", (time.monotonic() - st) * 1e3)
        self._tr_first_token(req)
        e = self._traces.get(req.id)
        if e is not None:
            nbytes = sum(
                int(a.nbytes) for a in jax.tree.leaves(payload)
            )
            self._tracer.record_span(
                "engine.kv_export", e["tid"], t0_w,
                (time.perf_counter() - t0_m) * 1e3,
                {"pages": k, "tail_bytes": nbytes},
            )
        self._tr_close(req.id, "handoff", generated=1)
        self.stats["requests_finished"] += 1
        with self._pending_lock:
            self._deadline_at.pop(req.id, None)
        return TokenEvent(
            request_id=req.id,
            token=tok,
            index=req.resumed_from,
            finished=True,
            finish_reason="handoff",
            logprob=first_logprob,
        )

    # afcheck: owns-pages success installs into the slot table; None returns custody to the caller's prefill path
    def _try_handoff_install(
        self, req: Request, free_slot: int, pages: list[int], start: int, kind: str
    ) -> list[TokenEvent] | None:
        """Phase 2 live install: when the prefix walk matched every full
        prompt page and the phase-1 tail payload was adopted, upload the
        tail page directly and install the slot with the phase-1 first
        token — zero prefill, and the slot state is bit-identical to what
        the prefill node would have decoded from. Any shortfall (walk fell
        short, payload missing/aged out, upload failure) returns None: the
        caller re-prefills the suffix normally and greedy re-samples the
        same first token — the token-exact fallback."""
        desc = req.handoff
        ps = self.ecfg.page_size
        L = len(req.prompt)
        k = (L - 1) // ps
        if (
            not isinstance(desc, dict)
            or desc.get("page_size") != ps
            or desc.get("prompt_tokens") != L
            or desc.get("pages") != k
            or not isinstance(desc.get("t0"), int)
            or isinstance(desc.get("t0"), bool)
            or start != k * ps
        ):
            self.stats["kv_handoff_fail_walk_total"] += 1
            return None
        with self._session_lock:
            entry = self._handoff_in.pop(str(desc.get("id")), None)
        if entry is None or entry[0] < time.monotonic():
            self.stats["kv_handoff_fail_stash_total"] += 1
            return None
        try:
            with self._session_lock:
                self._upload_page_kv([entry[1]], [pages[k]])
        except Exception:
            # harmless: the fallback prefills prompt[start:], which
            # rewrites the whole tail page
            self.stats["kv_handoff_fail_upload_total"] += 1
            return None
        self._dequeue_acquired(req, kind, start)
        row = build_page_table(pages, self.ecfg.max_pages_per_seq)
        self.stats["kv_handoff_completed_total"] += 1
        lp = desc.get("logprob")
        return [
            self._install(
                req, free_slot, pages, row, int(desc["t0"]),
                float(lp) if lp is not None else 0.0,
            )
        ]

    # afcheck: owns-pages the slot table takes custody; release_slot/preempt free them
    def _install(
        self,
        req: Request,
        slot_idx: int,
        pages: list[int],
        row: np.ndarray,
        tok: int,
        logprob: float,
    ) -> TokenEvent:
        if self._shared_prefix and not req.mm_embeds:
            # The prompt's KV is final once prefill completes: content-address
            # its full pages NOW so the rest of a burst (and any later
            # request) reuses them while this one is still decoding. Decode
            # writes land strictly past the prompt, so published pages are
            # never rewritten by their owner.
            with self._session_lock:
                self.allocator.publish(req.prompt, pages)
        st = self._submit_t.pop(req.id, None)
        if st is not None:
            # TTFT as the engine sees it: submit → first sampled token
            # (queue wait + prefill), the latency an agent loop waits on.
            self.latency.observe("ttft_ms", (time.monotonic() - st) * 1e3)
        self._tr_first_token(req)
        slot = _Slot(
            req=req,
            pages=pages,
            length=len(req.prompt),
            generated=1,
            last_token=tok,
            tokens=list(req.prompt) + [tok],
            draft_len=len(req.prompt),  # prefill replays onto the draft cache
        )
        event = self._emit(slot_idx, slot, tok, logprob)
        if not event.finished:
            s = req.sampling
            self.slots[slot_idx] = slot
            self.page_tables[slot_idx] = row
            self.seq_lens[slot_idx] = slot.length
            self.last_tokens[slot_idx] = tok
            self.temps[slot_idx] = s.temperature
            self.top_ks[slot_idx] = s.top_k
            self.top_ps[slot_idx] = s.top_p
            if req.grammar is not None:
                g = req.grammar
                with self._session_lock:
                    off = self._gbank_entries[id(g)]["off"]
                local = int(g.trans[g.start, tok])
                self.grammar_states[slot_idx] = off + local if local >= 0 else 0
                ids = list(s.stop_token_ids)[:_MAX_STOP_IDS]
                self.eos_ids[slot_idx, : len(ids)] = ids
        self._dirty = True
        self._compact = None  # membership changed
        return event

    def _prefill(self, tokens: list[int], start: int, row: np.ndarray):
        """Prefill `tokens` beginning at absolute position `start`, optionally
        in fixed-size chunks. start==0 with no chunking takes the flash-capable
        whole-prompt path; everything else flows through the cached-page
        attention path (which generalizes to any start). Returns the final
        position's logits."""
        chunk = self.ecfg.prefill_chunk
        pieces: list[tuple[int, list[int]]] = []
        if chunk is None or len(tokens) <= chunk:
            pieces.append((start, list(tokens)))
        else:
            for off in range(0, len(tokens), chunk):
                pieces.append((start + off, list(tokens[off : off + chunk])))

        last_logits = None
        for piece_start, piece in pieces:
            bucket = self.ecfg.prefill_bucket(len(piece))
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : len(piece)] = np.asarray(piece, np.int32)
            if piece_start == 0 and len(pieces) == 1:
                fn = _prefill_fn(self.prefill_cfg, self.ecfg, bucket, self.mesh)
                last_logits, self.cache.k_pages, self.cache.v_pages = fn(
                    self.params,
                    self.cache.k_pages,
                    self.cache.v_pages,
                    jnp.asarray(padded),
                    jnp.int32(len(piece)),
                    jnp.asarray(row),
                )
                self._draft_replay(
                    _prefill_fn, bucket,
                    jnp.asarray(padded), jnp.int32(len(piece)), jnp.asarray(row),
                )
            else:
                fn = _suffix_prefill_fn(self.prefill_cfg, self.ecfg, bucket)
                last_logits, self.cache.k_pages, self.cache.v_pages = fn(
                    self.params,
                    self.cache.k_pages,
                    self.cache.v_pages,
                    jnp.asarray(padded),
                    jnp.int32(piece_start),
                    jnp.int32(len(piece)),
                    jnp.asarray(row),
                )
                self._draft_replay(
                    _suffix_prefill_fn, bucket,
                    jnp.asarray(padded), jnp.int32(piece_start),
                    jnp.int32(len(piece)), jnp.asarray(row),
                    with_mesh=False,
                )
        return last_logits

    def _prefill_mm(self, tokens: list[int], row: np.ndarray, mm_embeds) -> jax.Array:
        """Multimodal whole-prompt prefill: placeholder positions take the
        provided embeddings instead of token-table rows."""
        bucket = self.ecfg.prefill_bucket(len(tokens))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(tokens)] = np.asarray(tokens, np.int32)
        inject = np.zeros((1, bucket, self.cfg.hidden_size), np.float32)
        mask = np.zeros((1, bucket), bool)
        for off, emb in mm_embeds:
            arr = np.asarray(emb, np.float32)
            inject[0, off : off + arr.shape[0]] = arr
            mask[0, off : off + arr.shape[0]] = True
        fn = _prefill_inject_fn(self.prefill_cfg, self.ecfg, bucket, self.mesh)
        last, self.cache.k_pages, self.cache.v_pages = fn(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(padded),
            jnp.asarray(inject),
            jnp.asarray(mask),
            jnp.int32(len(tokens)),
            jnp.asarray(row),
        )
        # The draft has no projector for the target's media embeddings; it
        # prefills the placeholder token ids instead. Verification keeps
        # correctness — a context-blind draft only lowers the acceptance
        # rate on multimodal rows.
        self._draft_replay(
            _prefill_fn, bucket,
            jnp.asarray(padded), jnp.int32(len(tokens)), jnp.asarray(row),
        )
        return last

    def _emit(
        self, slot_idx: int, slot: _Slot, tok: int, logprob: float | None = None
    ) -> TokenEvent:
        # Inter-token latency: the gap between consecutive token ARRIVALS of
        # one request, as a stream consumer would see them (span harvests
        # deliver their tokens together — those near-zero gaps are real).
        now = time.perf_counter()
        if slot.last_emit_t > 0.0:
            with self._telemetry_lock:
                self._itl_window.append(now - slot.last_emit_t)
            self.latency.observe("itl_ms", (now - slot.last_emit_t) * 1e3)
        slot.last_emit_t = now
        s = slot.req.sampling
        reason = None
        if tok in s.stop_token_ids:
            reason = "stop"
        elif slot.generated >= s.max_new_tokens:
            reason = "length"
        ev = TokenEvent(
            request_id=slot.req.id,
            token=tok,
            # resumed_from: a preempted-and-resumed request keeps one
            # uninterrupted index sequence across incarnations.
            index=slot.req.resumed_from + slot.generated - 1,
            finished=reason is not None,
            finish_reason=reason,
            logprob=logprob,
        )
        if ev.finished:
            self._tr_close(slot.req.id, reason or "stop", generated=slot.generated)
            self._release(slot_idx, slot)
        return ev

    def _release(self, slot_idx: int, slot: _Slot) -> None:
        if slot.req.spec_parent is not None:
            # Engine-internal speculative prefill: publish + stash instead
            # of session retention (docs/OPERATIONS.md "Agent-aware
            # serving") — the parent session's absorb/teardown owns the
            # pages from here.
            self._release_spec(slot_idx, slot)
            return
        sid = slot.req.session_id
        with self._session_lock:
            if self._shared_prefix and not slot.req.mm_embeds and len(slot.tokens) > 1:
                # Publish the GENERATED full pages too (the prompt's were
                # published at install; re-walking them is a cheap no-op):
                # agent→agent chains resubmit prompt+response as the next
                # prompt, so completed outputs are tomorrow's shared prefixes.
                # The last token's KV was never written — publish tokens[:-1].
                self.allocator.publish(slot.tokens[:-1], slot.pages)
            if (
                sid
                and self.ecfg.enable_prefix_cache
                and len(slot.tokens) > 1
                and not slot.req.mm_embeds
            ):
                # Retain the KV for the next turn. The last generated token's
                # KV was never written (it is returned, not fed back), so the
                # cached prefix is tokens[:-1]. Pages were sized for
                # prompt+max_new_tokens; free the tail that holds no KV
                # (early stop-token finishes would otherwise strand capacity).
                cached = slot.tokens[:-1]
                keep = -(-len(cached) // self.ecfg.page_size)
                if keep < len(slot.pages):
                    self.allocator.free(slot.pages[keep:])
                old = self._sessions.pop(sid, None)
                if old is not None:
                    self.allocator.free(old.pages)
                self._sessions[sid] = _SessionEntry(
                    pages=slot.pages[:keep], tokens=cached, last_used=time.time()
                )
                if self.ecfg.spec_prefill and slot.req.expect_followup:
                    # Agent-aware serving: pin the just-retained session and
                    # speculatively prefill declared candidate follow-ups in
                    # idle budget. Gated on spec_prefill so the knob-off
                    # scheduler is bit-compatible with today's.
                    self._agent_keepwarm_locked(sid, slot)
            else:
                self.allocator.free(slot.pages)
        self.stats["requests_finished"] += 1
        with self._pending_lock:
            self._deadline_at.pop(slot.req.id, None)
        if self.slots[slot_idx] is slot:
            self.slots[slot_idx] = None
        self.page_tables[slot_idx] = 0
        self.seq_lens[slot_idx] = 0
        self.temps[slot_idx] = 0.0
        self.top_ks[slot_idx] = 0
        self.top_ps[slot_idx] = 1.0
        self.grammar_states[slot_idx] = 0
        self.eos_ids[slot_idx] = -1
        with self._session_lock:
            self._grammar_release(slot.req.grammar)
        self._dirty = True
        self._compact = None  # membership changed

    def _release_spec(self, slot_idx: int, slot: _Slot) -> None:
        """Release a finished speculative prefill job: publish its pages
        (the candidate prefix is now content-addressed for the follow-up's
        acquisition walk to absorb) and STASH the refs in the parent
        session's speculation state instead of freeing — absorb or teardown
        settles them. A job whose state was already torn down (pin spilled,
        session cancelled mid-prefill) just frees; requests_finished is not
        bumped (internal work is not throughput)."""
        with self._session_lock:
            st = None
            for entry in self._spec_by_session.values():
                if slot.req.id in entry["cands"]:
                    st = entry
                    break
            if st is not None and self._shared_prefix and len(slot.tokens) > 1:
                self.allocator.publish(slot.tokens[:-1], slot.pages)
                st["stashes"][slot.req.id] = slot.pages
                t0 = st["t0"].get(slot.req.id)
                if st["tid"] is not None and t0 is not None:
                    # The speculative window, parent-attributed: enqueue →
                    # prefill done, with the candidate length it covered.
                    self._tracer.record_span(
                        "engine.spec_prefill", st["tid"], t0[0],
                        (time.perf_counter() - t0[1]) * 1e3,
                        {
                            "parent": st["parent"],
                            "tokens": len(st["cands"][slot.req.id]),
                        },
                    )
            else:
                self.allocator.free(slot.pages)
        with self._pending_lock:
            self._deadline_at.pop(slot.req.id, None)
        if self.slots[slot_idx] is slot:
            self.slots[slot_idx] = None
        self.page_tables[slot_idx] = 0
        self.seq_lens[slot_idx] = 0
        self.temps[slot_idx] = 0.0
        self.top_ks[slot_idx] = 0
        self.top_ps[slot_idx] = 1.0
        self.grammar_states[slot_idx] = 0
        self.eos_ids[slot_idx] = -1
        self._dirty = True
        self._compact = None  # membership changed

    def request_cancel(self, request_id: str) -> None:
        """Cancel a pending or active request (client gone / deadline hit):
        its slot and pages release at the next step() — work for a reader
        that no longer exists must not keep decoding."""
        self._cancels.add(request_id)

    def request_fork(self, src_id: str, new_id: str) -> None:
        """Fork a LIVE slot mid-decode (branch decoding's beam re-fork,
        docs/PREFIX_CACHING.md "Fork / COW branches"): at the next step()
        the source slot's KV is cloned copy-on-write — full pages incref'd,
        the partial tail page copied — into a new slot continuing from the
        same state under ``new_id``; its sampling diverges through the
        decode step's per-row RNG, and its TokenEvent indexes continue from
        the source's generated count (the consumer reads the fork point off
        the first event). If the source is gone or capacity is lacking when
        the command drains, the engine emits a terminal
        ``finish_reason="fork_failed"`` event for ``new_id`` so the caller's
        group accounting never hangs. Thread-safe."""
        with self._pending_lock:
            self._fork_cmds.append((src_id, new_id))

    def _apply_forks(self) -> list[TokenEvent]:
        """Drain queued live-fork commands (scheduler thread; the decode
        pipeline was harvested by the caller so slot state is current)."""
        with self._pending_lock:
            cmds, self._fork_cmds = self._fork_cmds, []
        events: list[TokenEvent] = []
        for src, new in cmds:
            if not self._fork_live(src, new):
                self.stats["branch_fork_failed_total"] += 1
                events.append(
                    TokenEvent(
                        request_id=new, token=-1, index=-1, finished=True,
                        finish_reason="fork_failed",
                    )
                )
        return events

    def _fork_live(self, src_id: str, new_id: str) -> bool:
        """Clone the live slot running ``src_id`` into a free slot under
        ``new_id``. Written KV = the source's first ``slot.length``
        positions: full pages are shared copy-on-write (decode writes land
        strictly past them), the partial tail page — which the clone both
        reads and will write — is privately copied. The clone's pending
        last token decodes independently from the next step on."""
        found = next(
            (
                (i, s)
                for i, s in enumerate(self.slots)
                if s is not None and s.req.id == src_id
            ),
            None,
        )
        if found is None:
            return False
        si, slot = found
        if slot.req.grammar is not None or slot.req.mm_embeds:
            return False  # same exclusions as install-time forking
        slot_idx = next(
            (i for i, s in enumerate(self.slots) if s is None), None
        )
        if slot_idx is None or self._slots_available() <= 0:
            return False
        ps = self.ecfg.page_size
        written = slot.length  # positions 0..length-1 hold KV (the pending
        # last token's KV is written by the NEXT decode step)
        full = written // ps
        total = len(slot.pages)
        with self._session_lock:
            fresh = self._alloc_with_eviction(total - full)
            if fresh is None:
                return False
            self.allocator.incref(slot.pages[:full])
        pages = slot.pages[:full] + fresh
        if written % ps:
            self._copy_page(slot.pages[full], fresh[0])
        child_req = dataclasses.replace(
            slot.req, id=new_id, n_branches=1, session_id=None
        )
        child = _Slot(
            req=child_req,
            pages=pages,
            length=slot.length,
            generated=slot.generated,
            last_token=slot.last_token,
            tokens=list(slot.tokens),
            draft_len=slot.draft_len,
        )
        self.slots[slot_idx] = child
        self.page_tables[slot_idx] = build_page_table(
            pages, self.ecfg.max_pages_per_seq
        )
        self.seq_lens[slot_idx] = child.length
        self.last_tokens[slot_idx] = child.last_token
        s = child_req.sampling
        self.temps[slot_idx] = s.temperature
        self.top_ks[slot_idx] = s.top_k
        self.top_ps[slot_idx] = s.top_p
        self.grammar_states[slot_idx] = 0
        self.eos_ids[slot_idx] = -1
        with self._pending_lock:
            # The clone inherits the source's remaining wall-clock budget:
            # a deadline-carrying group must not grow immortal branches.
            exp = self._deadline_at.get(src_id)
            if exp is not None:
                self._deadline_at[new_id] = exp
        self._dirty = True
        self._compact = None  # membership changed
        self.stats["branch_forks_total"] += 1
        self._tr_fork(src_id, new_id)
        return True

    def live_request_ids(self) -> list[str]:
        """Ids the engine currently holds (pending + mid-prefill + active).
        Advisory from other threads (defensive copies): the authoritative
        enumeration for the drain sweep happens on the scheduler thread
        inside step() (_expire_deadlines)."""
        with self._pending_lock:
            ids = [r.id for r in self.pending]
        ids += [j.req.id for j in list(self._prefill_jobs)]
        ids += [s.req.id for s in list(self.slots) if s is not None]
        return ids

    def deadline_all_now(self) -> int:
        """Graceful-drain helper: arm a sweep that gives every live request
        an already-expired deadline, so step() terminates each one with a
        finish_reason="deadline_exceeded" TokenEvent. Unlike request_cancel
        (which frees silently), every consumer gets a terminal event — a
        draining node must answer its callers, not strand them. The sweep
        itself runs ON the scheduler thread at the top of the next step()
        (_prefill_jobs/slots are worker-thread state; enumerating them here
        could race a concurrent step and miss a live request). Returns an
        advisory count for drain telemetry."""
        self._drain_sweep = True
        return len(self.live_request_ids())

    def _expire_deadlines(self) -> list[str]:
        """Scan Request.deadline_s expiries (empty-dict no-op when unused):
        expired ids route through the normal cancel path; the caller emits
        their terminal deadline_exceeded events. A pending drain sweep
        (deadline_all_now) is applied here first — ON the scheduler thread,
        where pending/jobs/slots can be enumerated without racing a step."""
        if self._drain_sweep:
            self._drain_sweep = False
            t0 = time.monotonic()
            with self._pending_lock:
                ids = [r.id for r in self.pending]
            ids += [j.req.id for j in self._prefill_jobs]
            ids += [s.req.id for s in self.slots if s is not None]
            with self._pending_lock:
                for rid in ids:
                    self._deadline_at[rid] = t0
        t = time.monotonic()
        with self._pending_lock:
            if not self._deadline_at:
                return []
            expired = [rid for rid, exp in self._deadline_at.items() if exp <= t]
            for rid in expired:
                del self._deadline_at[rid]
            if expired:
                # Classify queue-time sheds: an expiry whose id is still
                # PENDING never got a slot — that is the overload signal
                # (deadline-aware shedding), distinct from an active request
                # running out of budget mid-decode. Preempted-and-resumed
                # requests (resumed_from > 0) DID admit and decode before
                # landing back in the queue, so they count as mid-decode
                # budget exhaustion, not queue-time overload.
                pending_ids = {r.id for r in self.pending if r.resumed_from == 0}
                shed = sum(1 for rid in expired if rid in pending_ids)
                if shed:
                    self.stats["shed_pending_deadline_total"] += shed
        if expired:
            self._cancels.update(expired)
        return expired

    def _drain_cancels(self, expected: set[str] | None = None) -> None:
        """Apply queued cancels. `expected` ids (deadline expiries routed
        through this path) are exempt from the cancels_unknown accounting —
        they were live moments ago by construction."""
        if not self._cancels:
            return
        cancels, self._cancels = self._cancels, set()
        with self._pending_lock:
            for rid in cancels:
                self._deadline_at.pop(rid, None)
        matched: set[str] = set()
        with self._pending_lock:
            n_before = len(self.pending)
            dropped = [r for r in self.pending if r.id in cancels]
            kept = collections.deque(r for r in self.pending if r.id not in cancels)
            self.pending = kept
            self.stats["requests_cancelled"] += n_before - len(kept)
        if dropped:
            with self._session_lock:
                for r in dropped:
                    self._grammar_release(r.grammar)
                    if r.session_id and (
                        r.session_id in self._pins
                        or r.session_id in self._spec_by_session
                    ):
                        # A cancelled follow-up must not leave its session
                        # keep-warm: release the pin and any speculative
                        # stashes so the pages return to the pool now
                        # instead of riding the pin TTL.
                        self._unpin_session_locked(r.session_id)
            for r in dropped:
                self._req_hashes.pop(r.id, None)
                matched.add(r.id)
        for job in [j for j in self._prefill_jobs if j.req.id in cancels]:
            # Mid-prefill cancel (mixed scheduling): the job's pages hold a
            # partial prompt — release them without publishing anything.
            with self._session_lock:
                self.allocator.free(job.pages)
            self._prefill_jobs.remove(job)
            self.stats["requests_cancelled"] += 1
            matched.add(job.req.id)
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.req.id in cancels:
                matched.add(slot.req.id)
                # Incomplete output: release WITHOUT session retention.
                with self._session_lock:
                    self.allocator.free(slot.pages)
                    self._grammar_release(slot.req.grammar)
                    sid = slot.req.session_id
                    if sid and (
                        sid in self._pins or sid in self._spec_by_session
                    ):
                        # Same terminal-path audit as the pending drop above:
                        # cancel tears down the session's pin + spec state.
                        self._unpin_session_locked(sid)
                self.slots[i] = None
                self.page_tables[i] = 0
                self.seq_lens[i] = 0
                self.temps[i] = 0.0
                self.top_ks[i] = 0
                self.top_ps[i] = 1.0
                self.grammar_states[i] = 0
                self.eos_ids[i] = -1
                self._dirty = True
                self._compact = None
                self.stats["requests_cancelled"] += 1
        if self._spec_stalled:
            # Speculative jobs still sitting out a spec.stall delay are
            # cancellable too (their session was unpinned above): drop them
            # before they ever reach the queue.
            live = [e for e in self._spec_stalled if e[1].id not in cancels]
            if len(live) != len(self._spec_stalled):
                for _t, r in self._spec_stalled:
                    if r.id in cancels:
                        matched.add(r.id)
                        self.stats["requests_cancelled"] += 1
                self._spec_stalled = live
        for rid in matched:
            self._submit_t.pop(rid, None)
            self._tr_close(
                rid,
                "deadline_exceeded" if expected and rid in expected else "cancelled",
            )
        # Cancels that matched nothing: the client thinks a request is in
        # flight that the engine does not hold (finished already, or never
        # submitted). Silent disagreement hides bugs — count it.
        unknown = cancels - matched - (expected or set())
        if unknown:
            self.stats["cancels_unknown"] += len(unknown)

    def _victim_slot(self) -> tuple[int, _Slot] | None:
        """The slot a preemption would evict: lowest priority first, then the
        one holding the most pages (frees the most capacity), then the
        highest slot index (determinism). Grammar-constrained and multimodal
        slots are never preempted — a mid-schema DFA state cannot resume
        through a prompt re-submit, and mm prompts are excluded from the
        prefix cache, so their resume could never ride it."""
        best: tuple[tuple[int, int, int], int, _Slot] | None = None
        for i, s in enumerate(self.slots):
            if s is None or s.req.grammar is not None or s.req.mm_embeds:
                continue
            key = (s.req.priority, -len(s.pages), -i)
            if best is None or key < best[0]:
                best = (key, i, s)
        return (best[1], best[2]) if best is not None else None

    def _cand_starved(self, cand: Request) -> bool:
        """Would the candidate fail to admit THIS tick? True when no slot is
        free, or when not enough pages are allocatable beyond its cached
        prefix. ``free_pages`` counts refcount-0 cached pages as allocatable,
        but admission increfs the candidate's OWN LRU-resident prefix pages
        out of that pool before allocating the remainder — subtract the
        overlap, or the probe reports "not starved" in exactly the band
        where ``_acquire_pages_locked`` actually fails (a parked/published
        prefix) and the starvation fence never ages. Session-hit prefixes
        need no correction: sessions hold live references, so their pages
        are never in ``free_pages``."""
        if self._slots_available() <= 0:
            return True
        with self._session_lock:
            cached_pages = self._cached_prefix_len(cand) // self.ecfg.page_size
            evictable_overlap = 0
            host_overlap = 0
            if (
                cached_pages
                and self._shared_prefix
                and not (cand.session_id and cand.session_id in self._sessions)
            ):
                # One chain walk for both counts. HOST-tier prefix pages
                # count as cached (peek matches them: no prefill FLOPs)
                # but each restore CONSUMES a fresh HBM page — add them
                # back to the allocation need, or a host-heavy prefix
                # reads "not starved" in exactly the band where
                # admission's restore path fails on pages.
                evictable_overlap, host_overlap = self.allocator.prefix_overlap_pages(
                    cand.prompt[: len(cand.prompt) - 1],
                    hashes=self._prompt_hashes(cand),
                )
            return (
                self._pages_needed(cand) - cached_pages + host_overlap
                > self.allocator.free_pages - evictable_overlap
            )

    def _maybe_preempt(self) -> list[TokenEvent]:
        """Preempt-and-resume (docs/FAULT_TOLERANCE.md overload control):
        when the best pending candidate in the admit window out-prioritizes
        the lowest-priority active slot AND has been page/slot-starved for
        ``preempt_fence_ticks`` consecutive ticks, park that slot's KV in
        the shared-prefix index (refcount-0 cached — nothing is recomputed
        unless evicted) and re-queue its request at the queue head with the
        generated-so-far suffix folded into the prompt. No terminal event is
        emitted; resume re-admits through the normal shared-prefix path and
        continues token-exactly under greedy. The ``engine.preempt_storm``
        fault point forces a preemption regardless of priority or starvation
        (deterministic chaos testing). Returns any events harvested when a
        firing preemption drained the decode pipeline."""
        if not self.pending:
            self._preempt_starved_ticks = 0
            self._preempt_last_head = None
            return []
        victim = self._victim_slot()
        if victim is None:
            self._preempt_starved_ticks = 0
            self._preempt_last_head = None
            return []
        vi, vslot = victim
        storm = _engine_fault("engine.preempt_storm") is not None
        if storm:
            self.stats["preempt_storm_injected"] += 1
        else:
            if self.ecfg.preempt_fence_ticks <= 0:
                return []  # priority preemption disabled
            # The pending queue is priority-tier-ordered (_enqueue_locked),
            # so the head IS the highest-priority waiter — no window scan.
            with self._pending_lock:
                cand = self.pending[0] if self.pending else None
            if cand is None or cand.priority <= vslot.req.priority:
                self._preempt_starved_ticks = 0
                self._preempt_last_head = None
                return []
            # Is the candidate actually starved this tick? Two signals, OR'd:
            # the capacity arithmetic (no admissible slot, or not enough
            # allocatable pages beyond the cached prefix — catches starvation
            # a tick earlier than waiting for admission to fail), and the
            # head being STUCK since the previous probe — admission ran in
            # between and refused it, which covers allocation modes the
            # arithmetic cannot model (COW copies, session re-allocs).
            head_stuck = cand.id == self._preempt_last_head
            if self.ecfg.mixed_step and not self._mixed_eligible(cand):
                # A grammar/mm head admits only on classic ticks; while
                # mixed ticks service admission, its stuckness is
                # mode-INELIGIBILITY, not capacity starvation — preempting
                # would free pages that lower-priority mixed candidates
                # absorb, not the head. Require the capacity probe instead
                # (true page/slot starvation still ages the fence below).
                head_stuck = False
            self._preempt_last_head = cand.id
            if not head_stuck:
                # The fence is per-head: a NEW head (the old one admitted,
                # shed, or was cancelled) starts its own starvation count —
                # inherited ticks would let it preempt after far fewer than
                # preempt_fence_ticks starved ticks of its own.
                self._preempt_starved_ticks = 0
                if not self._cand_starved(cand):
                    return []
            self._preempt_starved_ticks += 1
            if self._preempt_starved_ticks < self.ecfg.preempt_fence_ticks:
                return []
        # Preemption mutates slots and host shadows, and the in-flight
        # pipelined step may still emit for the victim: drain it first so
        # bookkeeping reflects harvested state.
        events = self._harvest_inflight()
        if self.slots[vi] is not vslot:
            # The harvest finished the original victim (or freed its slot).
            if not storm:
                # the capacity the preemption wanted just appeared on its own
                self._preempt_starved_ticks = 0
                return events
            # A consumed storm injection must still break something if
            # anything preemptable remains — otherwise seeded chaos schedules
            # silently under-fire and preempt_storm_injected diverges from
            # preemptions_total.
            victim = self._victim_slot()
            if victim is None:
                return events  # engine drained itself: nothing to preempt
            vi, vslot = victim
        if not storm:
            # The drain may have finished a DIFFERENT slot, freeing the
            # capacity the candidate needs. Abort only when admission is now
            # GUARANTEED: a free slot plus enough pages even if every page
            # must be allocated fresh (COW copies and session re-allocs can
            # demand up to the full budget — an optimistic probe here would
            # reset the fence each cycle and the preemption would never
            # commit).
            with self._session_lock:
                free = self.allocator.free_pages
            if self._slots_available() > 0 and free >= self._pages_needed(cand):
                self._preempt_starved_ticks = 0
                return events
        self._preempt_slot(vi, vslot)
        self._preempt_starved_ticks = 0
        return events

    def _preempt_slot(self, slot_idx: int, slot: _Slot) -> None:
        """Evict one active slot WITHOUT a terminal event: park its KV in
        the prefix index and re-queue the request, its generated-so-far
        suffix appended to the prompt (PR 1's refcounted content-addressed
        cache is what makes this cheap — resume is a prefix hit, not a
        re-prefill). The last sampled token's KV was never written, so the
        parked prefix is tokens[:-1] and the resume prompt is the full
        tokens list: its suffix re-prefill recomputes exactly the pending
        last-token logits the next decode step would have used — token-exact
        under greedy."""
        req = slot.req
        with self._session_lock:
            if self._shared_prefix:
                self.allocator.park(slot.tokens[:-1], slot.pages)
            else:
                # No content index to park into: drop the pages; resume
                # re-prefills the full context (correct, just not cheap).
                self.allocator.free(slot.pages)
        resumed = dataclasses.replace(
            req,
            prompt=list(slot.tokens),
            sampling=dataclasses.replace(
                req.sampling,
                max_new_tokens=req.sampling.max_new_tokens - slot.generated,
            ),
            resumed_from=req.resumed_from + slot.generated,
            # Branch forking is a ONE-TIME install event: a preempted group
            # parent resumes as the single branch it now is — re-forking on
            # resume would mint sibling ids that collide with live branches.
            n_branches=1,
        )
        with self._pending_lock:
            # Front of its priority tier: the victim keeps its seniority —
            # the moment capacity frees (and no higher tier is waiting), it
            # resumes.
            self._enqueue_locked(resumed, senior=True)
        self._req_hashes.pop(req.id, None)  # prompt changed: re-hash on probe
        self.slots[slot_idx] = None
        self.page_tables[slot_idx] = 0
        self.seq_lens[slot_idx] = 0
        self.temps[slot_idx] = 0.0
        self.top_ks[slot_idx] = 0
        self.top_ps[slot_idx] = 1.0
        self.grammar_states[slot_idx] = 0
        self.eos_ids[slot_idx] = -1
        self._dirty = True
        self._compact = None  # membership changed
        self.stats["preemptions_total"] += 1
        self._tr_preempt(slot)

    def _mixed_eligible(self, req: Request) -> bool:
        """Mixed prefill jobs carry plain token prompts only: grammar
        first-token masks, multimodal inject buffers, branch forks
        (which need the prompt's last-token logits — a mixed tick reads
        back only sampled tokens) and handoff phases (export samples from
        the last-prompt-token logits; adoption installs a live slot with
        no prefill at all) are classic-tick features (such requests still
        admit through the classic path)."""
        return (
            req.grammar is None
            and not req.mm_embeds
            and req.n_branches <= 1
            and req.handoff is None
            and not req.handoff_export
        )

    def _mixed_tick_ready(self) -> bool:
        """Should this tick run the packed mixed dispatch? Yes while prefill
        jobs are mid-prompt, or when prompts wait behind active decodes —
        the head-of-line contention mixing exists to remove. Everything else
        (idle-engine bursts → batched flash prefill, constrained traffic →
        the grammar-masked decode step, empty queue → plain decode) falls
        through to the classic paths unchanged."""
        if not self.ecfg.mixed_step:
            return False
        for s in self.slots:
            if s is not None and s.req.grammar is not None:
                return False  # grammar mask is a classic-tick feature
        if self._prefill_jobs:
            return True
        if not self.pending or self.num_active == 0:
            return False
        if self._slots_available() <= 0:
            return False
        with self._pending_lock:
            head = self.pending[0] if self.pending else None
        return head is not None and self._mixed_eligible(head)

    def _start_mixed_jobs(self, room: int) -> None:
        """Admit pending requests into chunked prefill jobs while the tick
        has token room (``_acquire_pages_locked``'s cached-prefix probe
        decides each job's chunk start, so session and shared-prefix hits
        skip straight to their suffix).

        Fairness mirrors ``_try_admit``: the queue is priority-tier-ordered
        at enqueue, and a page-starved (or mixed-ineligible) head does not
        block it — the scan looks up to ``admit_window`` entries past the
        head, bypasses age the same ``_head_starved_ticks`` fence, and the
        fence collapses the window to strict FIFO so freed pages reach the
        head first. Candidates whose leading page chain matches an IN-FLIGHT
        job defer until that job publishes at install
        (``prefix_batch_deferrals``) instead of re-prefilling the prefix."""
        window = max(1, self.ecfg.admit_window)
        if self._head_starved_ticks >= self.ecfg.head_starve_fifo_ticks:
            window = 1  # anti-starvation fence: freed pages go to the head
            # (and a mixed-ineligible head drains the jobs — no new ones can
            # start past it — until a classic tick can admit it)
        job_leads = {j.lead_hash for j in self._prefill_jobs if j.lead_hash}
        with self._pending_lock:
            cands = [
                self.pending[i]
                for i in range(min(window + self.ecfg.max_batch, len(self.pending)))
            ]
        # The pending queue is priority-tier-ordered at enqueue
        # (_enqueue_locked): the positional scan already tries higher
        # tiers first, and the fairness/fence rules below behave exactly
        # as in the flat-priority scheduler.
        head = cands[0] if cands else None
        head_pending = head is not None
        head_blocked = False  # page-starved OR mixed-ineligible head
        admitted_past_head = False
        skips = 0
        for req in cands:
            if room <= 0 or self._slots_available() <= 0 or skips >= window:
                break
            if not self._mixed_eligible(req):
                # grammar/mm admit via classic ticks; scan past them like a
                # starved entry. A blocked HEAD ages the fence below, so
                # sustained mixed traffic cannot starve it: once the fence
                # trips, no new jobs start and the job queue drains, letting
                # a classic tick admit it.
                head_blocked = head_blocked or req is head
                skips += 1
                continue
            lead = None
            if self._shared_prefix and len(req.prompt) > self.ecfg.page_size:
                lead = self._prompt_hashes(req)[0]
                if lead in job_leads:
                    # an in-flight job is about to publish this same leading
                    # page: defer until it installs, then hit the index
                    self.stats["prefix_batch_deferrals"] += 1
                    skips += 1
                    continue
            acq = self._acquire_pages_locked(req)
            if acq is None:
                head_blocked = head_blocked or req is head
                skips += 1
                continue  # page-starved: scan past it (decode frees pages)
            pages, start, kind = acq
            if kind != "fresh":
                lead = None  # reused pages are already published/indexed
            self._dequeue_acquired(req, kind, start)
            row = build_page_table(pages, self.ecfg.max_pages_per_seq)
            self._prefill_jobs.append(
                _PrefillJob(
                    req=req, pages=pages, row=row, start=start, pos=start,
                    lead_hash=lead,
                )
            )
            if lead is not None:
                job_leads.add(lead)
            if req is head:
                head_pending = False
            elif skips > 0:
                # Entries were SKIPPED (starved/ineligible/deferred) before
                # this one — a genuine bypass (the head precedes everything
                # in the snapshot, so a still-pending head implies a skip).
                # Plain FIFO multi-admission admits the head first and
                # counts nothing, matching the classic scheduler's stat.
                admitted_past_head = True
                self.stats["admission_reorders"] += 1
            room -= len(req.prompt) - start
        if admitted_past_head and head_blocked:
            self._head_starved_ticks += 1
        elif head is not None and not head_pending:
            self._head_starved_ticks = 0  # the head itself admitted

    def _mixed_tick(self) -> list[TokenEvent] | None:
        """One token-budget tick (docs/MIXED_SCHEDULING.md): decode every
        active slot by one token AND advance admitting prompts by up to
        ``budget - n_active`` prefill-chunk tokens, in ONE jitted ragged
        forward. Decode inter-token latency is bounded by the budget
        instead of the longest waiting prompt, and admission no longer
        waits for a decode span to drain.

        Returns None when NO prefill token could ride the tick (every
        candidate page-starved/deferred and no job in flight): the caller
        falls through to the classic paths — a one-token-per-slot mixed
        forward would forfeit decode_span amortization for zero scheduling
        benefit."""
        budget = self.ecfg.mixed_step_budget
        active = [(i, s) for i, s in enumerate(self.slots) if s is not None]
        n_active = len(active)
        committed = sum(len(j.req.prompt) - j.pos for j in self._prefill_jobs)
        self._start_mixed_jobs(budget - n_active - committed)
        committed = sum(len(j.req.prompt) - j.pos for j in self._prefill_jobs)
        # Pad to the smallest bucket holding this tick's real tokens — a
        # light tick (few decodes, a chunk tail) pays a small forward.
        bucket = self.ecfg.mixed_bucket(n_active + committed)
        room = bucket - n_active
        chunks: list[tuple[_PrefillJob, int]] = []
        for job in self._prefill_jobs:  # FIFO: head jobs drain first
            if room <= 0:
                break
            n = min(len(job.req.prompt) - job.pos, room)
            if n > 0:
                chunks.append((job, n))
                room -= n
        if not chunks:
            return None  # nothing to mix: classic tick (span decode) instead
        rows = [
            (self.page_tables[i], int(self.seq_lens[i]), [int(self.last_tokens[i])])
            for i, _ in active
        ] + [
            (job.row, job.pos, job.req.prompt[job.pos : job.pos + n])
            for job, n in chunks
        ]
        rr = pack_ragged_rows(rows, self.ecfg.max_pages_per_seq, bucket)
        temps = np.zeros((bucket,), np.float32)
        top_ks = np.zeros((bucket,), np.int32)
        top_ps = np.ones((bucket,), np.float32)
        for j, (i, _) in enumerate(active):
            temps[rr.last_flat[j]] = self.temps[i]
            top_ks[rr.last_flat[j]] = self.top_ks[i]
            top_ps[rr.last_flat[j]] = self.top_ps[i]
        for j, (job, n) in enumerate(chunks):
            if job.pos + n == len(job.req.prompt):
                # the chunk reaches the prompt's last token: its logits
                # sample the request's FIRST generated token this tick
                s = job.req.sampling
                flat = rr.last_flat[n_active + j]
                temps[flat] = s.temperature
                top_ks[flat] = s.top_k
                top_ps[flat] = s.top_p
        fn = _mixed_step_fn(self.cfg, self.ecfg, bucket, self.mesh)
        toks, lps, self.cache.k_pages, self.cache.v_pages = fn(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(rr.tokens),
            jnp.asarray(rr.page_tables),
            jnp.asarray(rr.row_starts),
            jnp.asarray(rr.n_tokens),
            jnp.asarray(rr.ctx_lens),
            jnp.asarray(rr.seq_ids),
            self._next_rng(),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
        )
        toks_np, lps_np = np.asarray(toks), np.asarray(lps)
        events: list[TokenEvent] = []
        for j, (i, slot) in enumerate(active):
            flat = rr.last_flat[j]
            tok, logprob = int(toks_np[flat]), float(lps_np[flat])
            slot.length += 1
            slot.generated += 1
            slot.last_token = tok
            slot.tokens.append(tok)
            self.seq_lens[i] = slot.length
            self.last_tokens[i] = tok
            self.stats["decode_tokens"] += 1
            events.append(self._emit(i, slot, tok, logprob))
        for j, (job, n) in enumerate(chunks):
            job.pos += n
            self.stats["prefill_tokens"] += n
            if job.pos == len(job.req.prompt):
                flat = rr.last_flat[n_active + j]
                tok = int(toks_np[flat])
                logprob = float(lps_np[flat])
                self._prefill_jobs.remove(job)
                free_slot = next(i for i, s in enumerate(self.slots) if s is None)
                events.append(
                    self._install(job.req, free_slot, job.pages, job.row, tok, logprob)
                )
        if n_active:
            self.stats["decode_steps"] += 1
        carried = n_active + sum(n for _, n in chunks)
        self._tick_mode = "mixed"
        self._tick_carried = carried
        self.stats["mixed_ticks"] += 1
        self.stats["mixed_tokens"] += carried
        with self._telemetry_lock:
            self._tick_tokens.append(carried)
        # Host shadows advanced outside the device-chained decode state:
        # the next classic dispatch must rebuild from them.
        self._dirty = True
        self._compact = None
        return events

    def step(self) -> list[TokenEvent]:
        """One scheduler tick (see :meth:`_step_inner` for the scheduling
        contract). This wrapper is the observability shell
        (docs/OBSERVABILITY.md): it times the tick into the ``tick_ms``
        heartbeat histogram and appends one flight-recorder row — tick mode
        (classic/mixed/prefill/spec), batch composition, token load,
        free/host pages, and the overload counters — so the last
        ``AGENTFIELD_FLIGHT_TICKS`` ticks are always reconstructible. A
        step that RAISES records an ``error`` row first: the ring is the
        crash dump."""
        t0 = time.perf_counter()
        self._tick_mode = "decode"
        self._tick_carried = 0
        try:
            events = self._step_inner()
        except Exception as e:
            self.flight.record(
                {
                    "t": round(time.time(), 3),
                    "mode": "error",
                    "error": repr(e)[:300],
                    "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
                    "active": self.num_active,
                    "pending": len(self.pending),
                    "jobs": len(self._prefill_jobs),
                    "free_pages": self.allocator.free_pages,  # afcheck: ignore[guarded-by] crash-dump telemetry: one int read; a torn value beats holding a lock the failed step may still own
                }
            )
            raise
        dur_ms = (time.perf_counter() - t0) * 1e3
        active = self.num_active
        if events or active or self._prefill_jobs or self.pending:
            self.latency.observe("tick_ms", dur_ms)
            row = {
                "t": round(time.time(), 3),
                "mode": self._tick_mode,
                "dur_ms": round(dur_ms, 3),
                "active": active,
                "pending": len(self.pending),
                "jobs": len(self._prefill_jobs),
                "events": len(events),
                "finished": sum(1 for ev in events if ev.finished),
                "tokens": self._tick_carried or len(events),
                "free_pages": self.allocator.free_pages,  # afcheck: ignore[guarded-by] telemetry snapshot: scheduler-thread int read between ticks, same discipline as the heartbeat's free_pages read
                "host_pages": self.allocator.host_pages,  # afcheck: ignore[guarded-by] telemetry snapshot: ditto
                "preemptions_total": self.stats["preemptions_total"],
                "shed_pending_deadline_total": self.stats["shed_pending_deadline_total"],
                "deadline_exceeded": self.stats["deadline_exceeded"],
            }
            if self._tick_mode == "mixed":
                # token-budget utilization: real tokens / configured budget
                row["budget_util"] = round(
                    self._tick_carried / max(1, self.ecfg.mixed_step_budget), 3
                )
            self.flight.record(row)
        return events

    def latency_histograms(self) -> dict:
        """The engine's always-on latency histogram snapshots (TTFT /
        inter-token / queue-wait / tick-duration, ms buckets) — shipped on
        every heartbeat under ``latency_hist`` and re-exported by the
        control plane as per-node Prometheus histograms
        (metrics.export_engine_histograms)."""
        return self.latency.snapshot()

    def _step_inner(self) -> list[TokenEvent]:
        """One scheduler tick: admit (prefill) if possible, else decode —
        unless ``mixed_step`` is on and prompts are contending with active
        decodes, in which case ONE packed ragged forward carries a decode
        token per active slot plus prefill-chunk tokens for the admitting
        head (``_mixed_tick``, docs/MIXED_SCHEDULING.md).

        With ``async_decode`` the decode path is a one-deep pipeline: dispatch
        step N, then read step N-1's tokens while the device runs N. Any
        control-flow change (admission, cancel, all-finished) harvests the
        outstanding step first, so host bookkeeping and the device state agree
        before membership changes. A slot that finishes at step N-1 has one
        speculative token in flight; its output is discarded at harvest
        (dispatch order on the device stream makes its stale KV write land
        before any re-use of the freed pages)."""
        events: list[TokenEvent] = []
        expired = self._expire_deadlines()  # no-op when no deadlines are set
        if (self._cancels or self._fork_cmds) and self._inflight is not None:  # afcheck: ignore[guarded-by] racy truthiness peek like _cancels: a command landing after the peek is drained next step
            # Cancels/forks mutate slots/host shadows: drain the pipeline
            # first so a post-mutation rebuild starts from harvested state.
            events += self._harvest_inflight()
        self._drain_cancels(expected=set(expired))
        self._drain_spec_stalled()  # spec.stall releases (no-op when empty)
        if self._fork_cmds:  # afcheck: ignore[guarded-by] racy truthiness peek; _apply_forks swaps the list under the lock
            # After cancels: a prune-then-refork burst from a branch group
            # must see the pruned slots already freed (their pages fund the
            # clones).
            events += self._apply_forks()
        # Exactly-one-terminal-event: a request whose deadline expired the
        # same tick its in-flight step finished naturally just got its REAL
        # terminal from the pre-cancel harvest above — do not stack a
        # deadline_exceeded terminal on top of it.
        finished_now = {e.request_id for e in events if e.finished}
        for rid in expired:
            if rid in finished_now:
                continue
            # Terminal event for the consumer (tokens generated so far were
            # already streamed; -1 marks "no token carried").
            self.stats["deadline_exceeded"] += 1
            events.append(
                TokenEvent(
                    request_id=rid, token=-1, index=-1, finished=True,
                    finish_reason="deadline_exceeded",
                )
            )
        # Overload control: a starved higher-priority pending request may
        # preempt the lowest-priority active slot (parking its KV in the
        # prefix index for a near-free resume). Cheap no-op when the queue
        # is empty or priorities are flat.
        events += self._maybe_preempt()
        if self._mixed_tick_ready():
            # Mixed ticks are synchronous (the packed descriptors change
            # every tick): drain the decode pipeline so host shadows are
            # current before they are packed into the ragged dispatch. (The
            # classic path below drains it too whenever admission is
            # possible, so this costs nothing extra under contention.)
            events += self._harvest_inflight()
            mixed = self._mixed_tick()
            if mixed is not None:
                return events + mixed
            # no prefill token could ride the tick (page-starved/deferred
            # candidates, no jobs): classic admission retry + span decode
        if self.pending and self._slots_available() > 0:
            # Admission needs current state: drain the pipeline first. Only
            # do this when a slot is actually free — under full occupancy the
            # drain would serialize the pipeline every tick for an admission
            # that cannot happen (finishes surface via the normal
            # post-dispatch harvest, freeing a slot for the next tick).
            events += self._harvest_inflight()
            admitted = self._try_admit()
            if admitted:
                self._tick_mode = "prefill"
                return events + admitted
        if self.num_active == 0:
            return events + self._harvest_inflight()

        inf = self._inflight
        if inf is not None and (
            len(inf["slots"]) != self.num_active
            or any(self.slots[i] is not slot for i, slot in inf["slots"])
        ):
            # Membership changed since dispatch (a slot finished last
            # harvest): the device-chained control state no longer matches
            # the host shadows a rebuild would read. Sync: harvest the
            # outstanding step, then dispatch from current state.
            events += self._harvest_inflight()
            if self.num_active == 0:
                return events  # that harvest finished the last active slot
        prev, self._inflight = self._inflight, None
        self._dispatch_decode()
        events += self._apply_harvest(prev)
        if not self.ecfg.async_decode:
            events += self._harvest_inflight()
        return events

    def _spec_eligible(self, active_idx: list[int]) -> bool:
        """Speculation handles greedy AND sampled rows per-row in one
        dispatch (_spec_decode_fn modes); only grammar-constrained rows
        exclude the dispatch — grammar masks would make draft proposals
        unsampleable mid-schema. Checked per dispatch: a batch gains/loses
        eligibility as constrained requests come and go."""
        if self.draft_cache is None or not active_idx:
            return False
        idx = np.asarray(active_idx)
        if (self.grammar_states[idx] != 0).any():
            return False
        if any(self.slots[i].req.grammar is not None for i in active_idx):
            return False
        # At least one row must be able to ACCEPT proposals (greedy or
        # plain-temperature); an all-truncated batch would pay k+1 draft
        # forwards plus the wide verify to emit exactly 1 token per row —
        # strictly worse than one plain decode forward.
        can_accept = (self.temps[idx] <= 0) | (
            (self.top_ks[idx] == 0) & (self.top_ps[idx] >= 1.0)
        )
        return bool(can_accept.any())

    def _dispatch_decode(self) -> None:
        """Dispatch one decode step (no host sync) and record it in-flight."""
        active_idx = [i for i, s in enumerate(self.slots) if s is not None]
        counts = None
        if self._spec_eligible(active_idx):
            toks, lps, counts, compact = self._decode_spec_dispatch(active_idx)
            self._tick_mode = "spec"
            self.stats["decode_steps"] += 1
            self.stats["spec_steps"] += 1
        else:
            bucket = self._pick_decode_bucket(len(active_idx))
            if bucket is not None:
                toks, lps = self._decode_compact_dispatch(active_idx, bucket)
                compact = True
            else:
                toks, lps = self._decode_full_dispatch()
                compact = False
            self.stats["decode_steps"] += max(1, self.ecfg.decode_span)
        self._inflight = {
            "tokens": toks,
            "logprobs": lps,
            "counts": counts,
            "slots": [(i, self.slots[i]) for i in active_idx],
            "compact": compact,
        }

    def _draft_replay(self, fn_factory, bucket: int, *call_args, with_mesh=True) -> None:
        """Replay a prefill onto the DRAFT cache (logits discarded) so
        speculative proposals see the same context as the target. No-op
        without a draft model."""
        if self.draft_cache is None:
            return
        fn = (
            fn_factory(self.draft_prefill_cfg, self.ecfg, bucket, self.mesh)
            if with_mesh
            else fn_factory(self.draft_prefill_cfg, self.ecfg, bucket)
        )
        _, self.draft_cache.k_pages, self.draft_cache.v_pages = fn(
            self.draft_params,
            self.draft_cache.k_pages,
            self.draft_cache.v_pages,
            *call_args,
        )

    def _resync_draft(self, active_idx: list[int]) -> None:
        """Replay any tokens the draft cache missed (normal-decode fallback
        steps advance the target only) through a draft suffix prefill, so
        speculation resumes with full-context proposals instead of silently
        collapsing to ~zero acceptance."""
        for i in active_idx:
            slot = self.slots[i]
            if slot.draft_len >= slot.length:
                continue
            missing = slot.tokens[slot.draft_len : slot.length]  # tokens IS
            # the full prompt+generated history; positions index it directly
            bucket = self.ecfg.prefill_bucket(len(missing))
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : len(missing)] = np.asarray(missing, np.int32)
            self._draft_replay(
                _suffix_prefill_fn, bucket,
                jnp.asarray(padded), jnp.int32(slot.draft_len),
                jnp.int32(len(missing)), jnp.asarray(self.page_tables[i]),
                with_mesh=False,
            )
            slot.draft_len = slot.length

    def _decode_spec_dispatch(
        self, active_idx: list[int]
    ) -> tuple[jax.Array, jax.Array, jax.Array, bool]:
        """Speculative step: draft proposes, target verifies
        (engine._spec_decode_fn). Chains device control state exactly like
        the normal dispatches — lengths advance on-device by each row's
        accepted count. Low occupancy takes the compact (bucketed) control
        state so the W-wide verify doesn't pay max_batch width."""
        self._resync_draft(active_idx)
        bucket = self._pick_decode_bucket(len(active_idx))
        if bucket is not None:
            c = self._compact_state(active_idx, bucket)
            self._dirty = True  # full-width device state is now stale
        else:
            c = self._dev_state()
        fn = _spec_decode_fn(self.cfg, self.draft_cfg, self.ecfg, self.mesh)
        (
            toks, lps, counts, new_seq_lens, next_toks,
            self.cache.k_pages, self.cache.v_pages,
            self.draft_cache.k_pages, self.draft_cache.v_pages,
        ) = fn(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            self.draft_params,
            self.draft_cache.k_pages,
            self.draft_cache.v_pages,
            c["tokens"],
            c["seq_lens"],
            c["page_tables"],
            c["temps"],
            c["top_ks"],
            c["top_ps"],
            self._next_rng(),
        )
        c["tokens"], c["seq_lens"] = next_toks, new_seq_lens
        return toks, lps, counts, bucket is not None

    def _harvest_inflight(self) -> list[TokenEvent]:
        prev, self._inflight = self._inflight, None
        return self._apply_harvest(prev)

    def _apply_harvest(self, inf: dict | None) -> list[TokenEvent]:
        """Read a dispatched step's sampled tokens and apply them: advance
        host bookkeeping, emit events, release finished slots. Slots replaced
        since dispatch (finished or cancelled) discard their speculative
        token — object identity is the liveness check."""
        if inf is None:
            return []
        toks = np.asarray(inf["tokens"])  # [span, B]
        lps = np.asarray(inf["logprobs"])
        # Speculative steps emit a VARIABLE number of tokens per row (the
        # accepted prefix + correction); counts[row] gates the span loop.
        counts = np.asarray(inf["counts"]) if inf.get("counts") is not None else None
        out: list[TokenEvent] = []
        for t in range(toks.shape[0]):
            for j, (i, slot) in enumerate(inf["slots"]):
                if self.slots[i] is not slot:
                    continue  # finished/cancelled: discard its later span tokens
                row = j if inf["compact"] else i
                if counts is not None:
                    if t >= counts[row]:
                        continue
                    self.stats["spec_emitted"] += 1
                tok, logprob = int(toks[t, row]), float(lps[t, row])
                slot.length += 1
                if counts is not None:
                    slot.draft_len = slot.length  # spec steps write BOTH caches
                slot.generated += 1
                slot.last_token = tok
                slot.tokens.append(tok)
                self.seq_lens[i] = slot.length
                self.last_tokens[i] = tok
                if slot.req.grammar is not None:
                    # Mirror the device-side DFA advance so a dirty rebuild of
                    # the control arrays starts from the current state.
                    self.grammar_states[i] = max(
                        int(self._gbank_trans[self.grammar_states[i], tok]), 0
                    )
                self.stats["decode_tokens"] += 1
                out.append(self._emit(i, slot, tok, logprob))
        with self._telemetry_lock:
            self._tick_tokens.append(len(out))
        return out

    def _pick_decode_bucket(self, n_active: int) -> int | None:
        if not self.ecfg.decode_buckets:
            return None
        for b in sorted(self.ecfg.decode_buckets):
            if n_active <= b < self.ecfg.max_batch:
                return b
        return None

    def _dev_state(self) -> dict[str, jax.Array]:
        """Full-width device control state, rebuilt from the host shadows
        when dirty (shared by the normal and speculative full dispatches)."""
        if self._dirty:
            self._dev = {
                "tokens": jnp.asarray(self.last_tokens),
                "seq_lens": jnp.asarray(self.seq_lens),
                "page_tables": jnp.asarray(self.page_tables),
                "temps": jnp.asarray(self.temps),
                "top_ks": jnp.asarray(self.top_ks),
                "top_ps": jnp.asarray(self.top_ps),
                "gstates": jnp.asarray(self.grammar_states),
                "eos_ids": jnp.asarray(self.eos_ids),
            }
            self._dirty = False
        return self._dev

    def _decode_full_dispatch(self) -> tuple[jax.Array, jax.Array]:
        d = self._dev_state()
        bank = self._gbank_device()
        toks, lps, new_seq_lens, new_gstates, last_toks, self.cache.k_pages, self.cache.v_pages = (
            self._decode_jit(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                d["tokens"],
                d["seq_lens"],
                d["page_tables"],
                self._next_rng(),
                d["temps"],
                d["top_ks"],
                d["top_ps"],
                d["gstates"],
                bank["trans"],
                bank["accept"],
                d["eos_ids"],
            )
        )
        d["tokens"], d["seq_lens"], d["gstates"] = last_toks, new_seq_lens, new_gstates
        return toks, lps

    def _compact_state(self, active_idx: list[int], bucket: int) -> dict:
        """Bucketed device control state: the active slots' rows gathered
        into a [bucket]-wide batch (padding rows are inert: seq_len 0 writes
        to the garbage page). Cached while membership is stable; shared by
        the normal and speculative compact dispatches."""
        key = (tuple(active_idx), bucket)
        c = self._compact
        if c is None or c["key"] != key:
            n = len(active_idx)
            tokens = np.zeros((bucket,), np.int32)
            seq_lens = np.zeros((bucket,), np.int32)
            page_tables = np.zeros((bucket, self.ecfg.max_pages_per_seq), np.int32)
            temps = np.zeros((bucket,), np.float32)
            top_ks = np.zeros((bucket,), np.int32)
            top_ps = np.ones((bucket,), np.float32)
            tokens[:n] = self.last_tokens[active_idx]
            seq_lens[:n] = self.seq_lens[active_idx]
            page_tables[:n] = self.page_tables[active_idx]
            temps[:n] = self.temps[active_idx]
            top_ks[:n] = self.top_ks[active_idx]
            top_ps[:n] = self.top_ps[active_idx]
            gstates = np.zeros((bucket,), np.int32)
            eos_ids = np.full((bucket, _MAX_STOP_IDS), -1, np.int32)
            gstates[:n] = self.grammar_states[active_idx]
            eos_ids[:n] = self.eos_ids[active_idx]
            c = self._compact = {
                "key": key,
                "tokens": jnp.asarray(tokens),
                "seq_lens": jnp.asarray(seq_lens),
                "page_tables": jnp.asarray(page_tables),
                "temps": jnp.asarray(temps),
                "top_ks": jnp.asarray(top_ks),
                "top_ps": jnp.asarray(top_ps),
                "gstates": jnp.asarray(gstates),
                "eos_ids": jnp.asarray(eos_ids),
            }
        return c

    def _decode_compact_dispatch(
        self, active_idx: list[int], bucket: int
    ) -> tuple[jax.Array, jax.Array]:
        """Low-occupancy step: gather the active slots' control rows into a
        [bucket]-wide batch (padding rows are inert: seq_len 0 writes to the
        garbage page). The jitted decode retraces once per bucket width.
        While membership is stable the compact control state stays
        device-resident (tokens/seq_lens advance on-device via the decode
        return); admission/release invalidates it."""
        c = self._compact_state(active_idx, bucket)
        bank = self._gbank_device()
        toks, lps, new_seq_lens, new_gstates, last_toks, self.cache.k_pages, self.cache.v_pages = (
            self._decode_jit(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                c["tokens"],
                c["seq_lens"],
                c["page_tables"],
                self._next_rng(),
                c["temps"],
                c["top_ks"],
                c["top_ps"],
                c["gstates"],
                bank["trans"],
                bank["accept"],
                c["eos_ids"],
            )
        )
        c["tokens"], c["seq_lens"], c["gstates"] = last_toks, new_seq_lens, new_gstates
        self._dirty = True  # full-width device state is now stale
        return toks, lps

    def run_to_completion(self, requests: list[Request]) -> dict[str, list[int]]:
        """Convenience driver: submit everything, step until drained, return
        generated token lists (streaming callers use step() directly)."""
        for r in requests:
            self.submit(r)
        results: dict[str, list[int]] = {r.id: [] for r in requests}
        while self.has_work():
            for ev in self.step():
                if ev.token >= 0:  # deadline/error terminals carry no token
                    # setdefault: branch forks emit under sibling ids the
                    # caller never submitted (branching.branch_rid)
                    results.setdefault(ev.request_id, []).append(ev.token)
        return results
