"""Token sampling for the serving engine.

Replaces the reference's provider-side sampling knobs (temperature etc. were
passed through litellm — sdk/python/agentfield/agent_ai.py:329-343). Greedy
and temperature sampling are vectorized over the decode batch so mixed
per-request settings share one jitted step (no shape specialization).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    top_p: float = 1.0  # 1 → disabled
    max_new_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


@functools.partial(jax.jit, static_argnames=("k_max",))
def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    rng: jax.Array,
    temperatures: jax.Array,  # [B] float32; <=0 → greedy for that row
    top_ks: jax.Array,  # [B] int32; 0 → disabled  (applied as top-K_MAX prefilter)
    top_ps: jax.Array,  # [B] float32; >=1 → disabled
    k_max: int = 64,  # static prefilter width for top-k/top-p rows
) -> jax.Array:
    """Vectorized mixed-strategy sampling. Rows with temperature<=0 take the
    argmax. Rows with plain temperature sampling (top_k=0, top_p>=1) sample the
    FULL tempered vocab. Rows requesting top-k and/or top-p truncation sample
    inside a static ``k_max``-wide candidate set (one lax.top_k scan, no vocab
    sort), with one exception that keeps the realized distribution honest:

    - requested ``top_k`` values larger than ``k_max`` are clamped to ``k_max``;
    - ``top_p``-only rows (top_k=0, top_p<1) whose nucleus is WIDER than the
      ``k_max`` most likely tokens (high temperature / flat distribution) fall
      back to exact full-vocab nucleus sampling — a [B, V] sort, paid only on
      steps where such a row exists (lax.cond), instead of silently narrowing
      the distribution to k_max candidates as pre-round-3 versions did.
    """
    B, V = logits.shape
    k_max = min(k_max, V)  # tiny vocabs: the prefilter can't exceed V
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    rng_full, rng_trunc = jax.random.split(rng)
    truncated_row = (top_ks > 0) | (top_ps < 1.0)

    def _sampled(_):
        # Full-vocab tempered sampling (exact for untruncated rows).
        full = jax.random.categorical(rng_full, logits / temps, axis=-1).astype(jnp.int32)

        def _with_trunc(_):
            # Truncated path inside the k_max candidate set.
            vals, idxs = jax.lax.top_k(logits, k_max)  # [B, k_max] descending
            scaled = vals / temps
            ranks = jnp.arange(k_max, dtype=jnp.int32)[None, :]
            k_eff = jnp.where(
                top_ks[:, None] > 0, jnp.minimum(top_ks[:, None], k_max), k_max
            )
            k_mask = ranks < k_eff
            # nucleus mask on the tempered distribution (keep first token)
            probs = jax.nn.softmax(jnp.where(k_mask, scaled, -jnp.inf), axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            p_mask = (cum - probs) < jnp.minimum(top_ps, 1.0)[:, None]
            masked = jnp.where(k_mask & p_mask, scaled, -jnp.inf)
            choice = jax.random.categorical(rng_trunc, masked, axis=-1)
            trunc = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

            # Exact wide-nucleus fallback: how much FULL-vocab tempered
            # probability mass do the k_max candidates hold? A top_p-only row
            # whose candidates hold less than its top_p has a nucleus wider
            # than the prefilter; sample it over the full sorted vocab.
            cand_mass = jnp.exp(
                jax.nn.logsumexp(scaled, axis=-1)
                - jax.nn.logsumexp(logits / temps, axis=-1)
            )
            need_exact = (top_ks == 0) & (top_ps < 1.0) & (cand_mass < top_ps)

            def _exact_rows(_):
                order = jnp.argsort(-logits, axis=-1)  # [B, V] descending
                svals = jnp.take_along_axis(logits, order, axis=-1) / temps
                p_full = jax.nn.softmax(svals, axis=-1)
                cum_f = jnp.cumsum(p_full, axis=-1)
                keep = (cum_f - p_full) < top_ps[:, None]
                ch = jax.random.categorical(
                    rng_trunc, jnp.where(keep, svals, -jnp.inf), axis=-1
                )
                exact = jnp.take_along_axis(order, ch[:, None], axis=-1)[:, 0].astype(
                    jnp.int32
                )
                return jnp.where(need_exact, exact, trunc)

            trunc = jax.lax.cond(
                jnp.any(need_exact), _exact_rows, lambda _: trunc, None
            )
            return jnp.where(truncated_row, trunc, full)

        sampled = jax.lax.cond(
            jnp.any(truncated_row), _with_trunc, lambda _: full, None
        )
        return jnp.where(temperatures <= 0, greedy, sampled)

    # Data-dependent runtime skips: an all-greedy batch (the agentic common
    # case) pays neither the categorical nor the top-k machinery; a batch
    # with no truncated rows skips the top-k sort.
    return jax.lax.cond(
        jnp.any(temperatures > 0), _sampled, lambda _: greedy, None
    )
