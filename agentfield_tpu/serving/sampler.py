"""Token sampling for the serving engine.

Replaces the reference's provider-side sampling knobs (temperature etc. were
passed through litellm — sdk/python/agentfield/agent_ai.py:329-343). Greedy
and temperature sampling are vectorized over the decode batch so mixed
per-request settings share one jitted step (no shape specialization).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    top_p: float = 1.0  # 1 → disabled
    max_new_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    rng: jax.Array,
    temperatures: jax.Array,  # [B] float32; <=0 → greedy for that row
    top_ks: jax.Array,  # [B] int32; 0 → disabled  (applied as top-K_MAX prefilter)
    top_ps: jax.Array,  # [B] float32; >=1 → disabled
    k_max: int = 64,  # static prefilter width for top-k/top-p rows
) -> jax.Array:
    """Vectorized mixed-strategy sampling. Rows with temperature<=0 take the
    argmax. Rows with plain temperature sampling (top_k=0, top_p>=1) sample the
    FULL tempered vocab. Rows requesting top-k and/or top-p truncation sample
    inside a static ``k_max``-wide candidate set (one lax.top_k scan, no vocab
    sort). This is a stated contract, not just an optimization:

    - requested ``top_k`` values larger than ``k_max`` are clamped to ``k_max``;
    - ``top_p``-only rows (top_k=0, top_p<1) are ALSO bounded by the ``k_max``
      most likely tokens — if the nucleus is wider than ``k_max`` (high
      temperature / flat distribution), the realized distribution is narrower
      than requested. Raise ``k_max`` if exact wide-nucleus sampling matters;
      cost grows with one [B, k_max] top_k + softmax.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    rng_full, rng_trunc = jax.random.split(rng)
    truncated_row = (top_ks > 0) | (top_ps < 1.0)

    def _sampled(_):
        # Full-vocab tempered sampling (exact for untruncated rows).
        full = jax.random.categorical(rng_full, logits / temps, axis=-1).astype(jnp.int32)

        def _with_trunc(_):
            # Truncated path inside the k_max candidate set.
            vals, idxs = jax.lax.top_k(logits, k_max)  # [B, k_max] descending
            scaled = vals / temps
            ranks = jnp.arange(k_max, dtype=jnp.int32)[None, :]
            k_eff = jnp.where(
                top_ks[:, None] > 0, jnp.minimum(top_ks[:, None], k_max), k_max
            )
            k_mask = ranks < k_eff
            # nucleus mask on the tempered distribution (keep first token)
            probs = jax.nn.softmax(jnp.where(k_mask, scaled, -jnp.inf), axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            p_mask = (cum - probs) < jnp.minimum(top_ps, 1.0)[:, None]
            masked = jnp.where(k_mask & p_mask, scaled, -jnp.inf)
            choice = jax.random.categorical(rng_trunc, masked, axis=-1)
            trunc = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
            return jnp.where(truncated_row, trunc, full)

        sampled = jax.lax.cond(
            jnp.any(truncated_row), _with_trunc, lambda _: full, None
        )
        return jnp.where(temperatures <= 0, greedy, sampled)

    # Data-dependent runtime skips: an all-greedy batch (the agentic common
    # case) pays neither the categorical nor the top-k machinery; a batch
    # with no truncated rows skips the top-k sort.
    return jax.lax.cond(
        jnp.any(temperatures > 0), _sampled, lambda _: greedy, None
    )
