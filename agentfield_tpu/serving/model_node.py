"""Model node: the TPU serving engine exposed as a control-plane node.

This is the piece that has no analogue in the reference — there, ``ai()``
left the cluster via litellm (agent_ai.py:342). Here a model node registers
like any agent node (kind="model") with a single ``generate`` reasoner, so
placement, health, status, DAG tracking and webhooks all apply to LLM calls
for free, and N concurrent ``ai()`` calls across the cluster coalesce into
shared decode steps in one engine (SURVEY §2.4 serving row).

The engine runs on a dedicated thread (JAX compute must not block the event
loop); completions resolve asyncio futures on the loop.
"""

from __future__ import annotations

import asyncio

from agentfield_tpu._compat import aio_timeout
import collections
import time
from typing import Any

import jax

from agentfield_tpu import tracing
from agentfield_tpu.branching import BranchGroup, validate_branch_spec
from agentfield_tpu.models import get_config, init_params
from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    QueueFullError,
    Request,
    RequestTooLongError,
)


class NodeDrainingError(QueueFullError):
    """The node is draining (rolling restart): admission is closed. A
    QueueFullError subclass so every transport surface already maps it to
    retryable backpressure (HTTP 503 / gRPC RESOURCE_EXHAUSTED) and SDK
    failover routes the caller to another node."""
from agentfield_tpu.serving.sampler import SamplingParams
from agentfield_tpu.sdk.agent import Agent


class ByteTokenizer:
    """Trivial byte-level tokenizer for demos/tests with random-weight models
    (real checkpoints use the HF tokenizer adapter).

    Caveat: decode(encode(x)) is lossy for ids >= 256, so TEXT-level
    multi-turn prompts won't prefix-match the session KV cache through this
    tokenizer — pass `tokens` for session reuse in demos (real tokenizers
    round-trip their own output)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size
        self.eos_token_id = 0  # NUL: never legal inside generated text

    def encode(self, text: str) -> list[int]:
        return [b % self.vocab_size for b in text.encode("utf-8")]

    def decode(self, tokens: list[int]) -> str:
        return bytes(t % 256 for t in tokens).decode("utf-8", errors="replace")

    def token_bytes(self, vocab_size: int) -> list[bytes]:
        """Per-id byte strings for grammar compilation (serving/grammar.py).
        Ids ≥ 256 alias low bytes through decode(), but for constrained
        decoding they are redundant — map them to NUL so the grammar only
        ever selects the canonical single-byte ids."""
        out = [bytes([i]) for i in range(min(256, vocab_size))]
        out += [b"\x00"] * (vocab_size - len(out))
        return out


class HFTokenizer:
    """transformers AutoTokenizer adapter (for real Llama checkpoints)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.vocab_size = self._tok.vocab_size
        self.eos_token_id = self._tok.eos_token_id

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, tokens: list[int]) -> str:
        return self._tok.decode(tokens)

    def token_bytes(self, vocab_size: int) -> list[bytes]:
        """Per-id byte strings for grammar compilation. Handles the two HF
        vocab conventions: byte-level BPE (GPT-2/Llama-3 — chars map through
        the bytes↔unicode table) and SentencePiece (▁ = space, <0xXX> = raw
        byte). Special tokens map to NUL (never legal inside JSON), so the
        grammar can't select them; EOS reaches the sampler via the accept-
        state allowance instead."""
        out = [b"\x00"] * vocab_size
        special = set(self._tok.all_special_ids or [])
        # GPT-2 byte-level BPE unicode → byte inverse table (the canonical
        # bytes_to_unicode mapping, inverted).
        bs = list(range(0x21, 0x7F)) + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        uni2byte = {chr(c): b for b, c in zip(bs, cs)}
        vocab = self._tok.get_vocab()
        byte_level = any(tok.startswith("Ġ") for tok in vocab)
        for tok, idx in vocab.items():
            if idx >= vocab_size:
                continue
            if idx in special:
                continue  # stays NUL
            if tok.startswith("<0x") and tok.endswith(">") and len(tok) == 6:
                try:
                    out[idx] = bytes([int(tok[3:5], 16)])
                    continue
                except ValueError:
                    pass
            if byte_level:
                try:
                    out[idx] = bytes(uni2byte[c] for c in tok)
                    continue
                except KeyError:
                    pass
            out[idx] = tok.replace("▁", " ").encode("utf-8")
        return out


def _prompt_byte_ids(text: str, max_chars: int):
    """UTF-8 prompt → ([1, max_chars] int32 padded byte ids, #bytes used,
    #bytes truncated). The one truncation recipe for the byte-level output
    heads (TTS, image gen): a cut landing mid-codepoint strips ONLY the
    incomplete trailing multibyte sequence (a complete final char stays) so
    the heads never condition on dangling continuation bytes."""
    import numpy as np

    full = text.encode("utf-8")
    data = full
    if len(full) > max_chars:
        data = full[:max_chars]
        i = len(data) - 1
        while i >= 0 and (data[i] & 0xC0) == 0x80:
            i -= 1
        if i >= 0 and data[i] >= 0xC0:
            lead = data[i]
            need = 2 if lead < 0xE0 else 3 if lead < 0xF0 else 4
            if len(data) - i < need:
                data = data[:i]
    ids = np.zeros((1, max_chars), np.int32)
    if data:
        ids[0, : len(data)] = np.frombuffer(data, np.uint8)
    return ids, len(data), len(full) - len(data)


def _resolve_tower(spec, configs: dict, get_cfg, load_ckpt, out_dim: int):
    """Shared tower resolution for string specs: registered config NAMES win
    over same-named cwd paths; a checkpoint DIRECTORY loads pretrained
    weights in bf16 (serving dtype — the MXU fast path; parity tests load
    f32 themselves); anything else goes through get_cfg for its
    known-names error. Non-strings pass through untouched."""
    if not isinstance(spec, str):
        return spec
    import os as _os

    if spec in configs:
        return get_cfg(spec)
    if _os.path.isdir(spec):
        return load_ckpt(spec, out_dim=out_dim, dtype="bfloat16")
    return get_cfg(spec)  # raises with known names


def load_draft_model(source: str, target_vocab: int, seed: int = 0):
    """Resolve a speculative-decoding draft: an HF checkpoint dir loads
    trained weights, a preset name random-inits (demo/tests — worst-case
    acceptance against an unrelated target). Returns the (params, cfg) pair
    InferenceEngine(draft=...) takes; rejects vocabulary mismatches up front
    (speculation compares token ids)."""
    import os as _os

    if _os.path.isdir(source):
        from agentfield_tpu.models.hf_loader import load_hf_checkpoint

        dcfg, dparams = load_hf_checkpoint(source)
    else:
        dcfg = get_config(source)
        dparams = init_params(dcfg, jax.random.PRNGKey(seed))
    if dcfg.vocab_size != target_vocab:
        raise ValueError(
            f"spec draft {source!r} vocab {dcfg.vocab_size} != "
            f"target vocab {target_vocab}"
        )
    return dparams, dcfg


def _error_event(rid: str, error: str):
    from agentfield_tpu.serving.engine import TokenEvent

    return TokenEvent(request_id=rid, token=-1, index=-1, finished=True, finish_reason=f"error: {error}")


class ModelBackend:
    def __init__(
        self,
        params: Any,
        cfg: LlamaConfig,
        ecfg: EngineConfig | None = None,
        tokenizer=None,
        seed: int = 0,
        idle_sleep: float = 0.002,
        model_name: str = "custom",
        mesh=None,
        vision=None,  # vision tower: config name, VisionConfig, or
        # (VisionConfig, params). A name/config gets random-init params
        # (plumbing + tests; checkpoint loading hands params in directly).
        # None → image inputs are rejected with a clear error.
        grammar_whitespace: bool = False,  # constrained output may carry
        # bounded whitespace (grammar.py v2) instead of canonical compact JSON
        audio=None,  # audio input tower: config name, AudioConfig, or
        # (AudioConfig, params) — serve <audio> prompt parts (models/audio.py)
        tts=None,  # audio OUTPUT head: config name, TTSConfig, or
        # (TTSConfig, params) — serve output="audio"/"speech" synthesis
        imagegen=None,  # image OUTPUT head: config name, ImageGenConfig, or
        # (ImageGenConfig, params) — serve output="image" rendering
        draft=None,  # (params, cfg) speculative-decoding draft model
        # (with ecfg.spec_k > 0; see InferenceEngine)
    ):
        self.grammar_whitespace = grammar_whitespace
        self.cfg = cfg
        self.model_name = model_name
        self.engine = InferenceEngine(
            params, cfg, ecfg, seed=seed, mesh=mesh, draft=draft
        )
        self.tokenizer = tokenizer
        self.vision_cfg = self.vision_params = None
        if vision is not None:
            import jax as _jax

            from agentfield_tpu.models.vision import (
                VisionConfig,
                get_vision_config,
                init_vision_params,
            )

            from agentfield_tpu.models.vision import CONFIGS as _VCFGS
            from agentfield_tpu.models.vision import load_clip_vision

            vision = _resolve_tower(
                vision, _VCFGS, get_vision_config, load_clip_vision,
                cfg.hidden_size,
            )
            if isinstance(vision, VisionConfig):
                self.vision_cfg = vision
                self.vision_params = init_vision_params(vision, _jax.random.PRNGKey(seed + 1))
            else:
                self.vision_cfg, self.vision_params = vision
            if self.vision_cfg.out_dim != cfg.hidden_size:
                raise ValueError(
                    f"vision out_dim={self.vision_cfg.out_dim} must match the "
                    f"LM hidden_size={cfg.hidden_size}"
                )
        # Audio towers mirror the vision contract: a name/config random-inits
        # (plumbing + tests), (cfg, params) serves trained weights.
        self.audio_cfg = self.audio_params = None
        if audio is not None:
            import jax as _jax

            from agentfield_tpu.models.audio import (
                AudioConfig,
                get_audio_config,
                init_audio_params,
            )

            from agentfield_tpu.models.audio import CONFIGS as _ACFGS
            from agentfield_tpu.models.audio import load_whisper_encoder

            audio = _resolve_tower(
                audio, _ACFGS, get_audio_config, load_whisper_encoder,
                cfg.hidden_size,
            )
            if isinstance(audio, AudioConfig):
                self.audio_cfg = audio
                self.audio_params = init_audio_params(audio, _jax.random.PRNGKey(seed + 2))
            else:
                self.audio_cfg, self.audio_params = audio
            if self.audio_cfg.out_dim != cfg.hidden_size:
                raise ValueError(
                    f"audio out_dim={self.audio_cfg.out_dim} must match the "
                    f"LM hidden_size={cfg.hidden_size}"
                )
        self.tts_cfg = self.tts_params = None
        if tts is not None:
            import jax as _jax

            from agentfield_tpu.models.audio import (
                TTSConfig,
                get_tts_config,
                init_tts_params,
            )

            if isinstance(tts, str):
                tts = get_tts_config(tts)
            if isinstance(tts, TTSConfig):
                self.tts_cfg = tts
                self.tts_params = init_tts_params(tts, _jax.random.PRNGKey(seed + 3))
            else:
                self.tts_cfg, self.tts_params = tts
        self.imagegen_cfg = self.imagegen_params = None
        if imagegen is not None:
            import jax as _jax

            from agentfield_tpu.models.image_gen import (
                ImageGenConfig,
                get_imagegen_config,
                init_imagegen_params,
            )

            if isinstance(imagegen, str):
                imagegen = get_imagegen_config(imagegen)
            if isinstance(imagegen, ImageGenConfig):
                self.imagegen_cfg = imagegen
                self.imagegen_params = init_imagegen_params(
                    imagegen, _jax.random.PRNGKey(seed + 5)
                )
            else:
                self.imagegen_cfg, self.imagegen_params = imagegen
        self.idle_sleep = idle_sleep
        # Graceful drain (SIGTERM / rolling restart): once set, _submit
        # refuses new work with NodeDrainingError while in-flight requests
        # run to completion (or deadline out at the drain grace cutoff).
        self._draining = False
        # One accumulation dict: (token, logprob) records per request —
        # parallel dicts would need mirrored lifecycle at every cleanup site.
        self._buffers: dict[str, list[tuple[int, float | None]]] = {}
        self._futures: dict[str, asyncio.Future] = {}
        self._streams: dict[str, asyncio.Queue] = {}  # rid -> per-token queue
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._next = 0
        # Compiled-grammar cache: canonical schema JSON -> Grammar (LRU,
        # bounded — each entry is an [n_states, vocab] table, tens of MB at a
        # real vocab). Grammar objects are also the engine's bank-dedup key,
        # so reusing the cached instance means one bank registration per
        # schema. In-flight compiles dedup through _grammar_futs.
        self._grammars: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        self._grammars_max = 8
        self._grammar_futs: dict[str, asyncio.Future] = {}
        # Cluster prefix tier (docs/PREFIX_CACHING.md "Cluster tier"):
        # cross-node KV page transfer. The fetch transport is the node's
        # gateway channel (build_model_node wires channel_server.fetch_kv);
        # $AGENTFIELD_KV_FETCH=0 is the node-local safety valve — the node
        # then neither pulls pages nor honors kv_peer hints (it still
        # SERVES peers' fetches; disable those by dropping the sketch via
        # EngineConfig.prefix_sketch_bytes=0).
        import os as _os

        self._kv_fetch_fn = None  # async (peer, chains_hex, timeout_s,
        # handoff=None) -> pages|None; the handoff kwarg is only passed
        # when set (3-arg test doubles stay valid)
        self.kv_fetch_enabled = _os.environ.get(
            "AGENTFIELD_KV_FETCH", "1"
        ).lower() not in ("0", "false", "no")
        self.kv_fetch_timeout_s = 5.0
        # In-flight prefetch dedup, keyed (peer, first missing chain): a
        # same-prefix burst landing on a cold node must issue ONE transfer,
        # not one per request — followers await the leader's adoption and
        # let admission's ordinary lookup find the pages.
        self._kv_prefetch_inflight: dict[tuple[str, bytes], asyncio.Future] = {}  # guarded by: external(node event loop — leader/follower dedup runs on one loop)
        # Branch decoding (docs/PREFIX_CACHING.md "Fork / COW branches"):
        # every branch rid maps to its group; the drive loop routes branch
        # TokenEvents here INSTEAD of the per-rid future/stream sinks, the
        # group prunes/reforks through the engine's request_cancel /
        # request_fork paths, and resolution delivers the WINNER to the
        # one client-visible sink (pruned branches emit no client frames).
        self._groups: dict[str, BranchGroup] = {}
        self._group_sinks: dict[str, tuple[str, Any]] = {}  # parent rid ->
        # ("future", fut) | ("stream", queue)
        self._group_meta: dict[str, dict] = {}  # parent rid -> "branches"
        # result block, for the streaming transports to attach post-replay
        self._group_tasks: set[asyncio.Task] = set()  # strong refs: a GC'd
        # resolution task would strand the group's sink forever
        # Control-plane verifier hook: async (target, payload) -> result
        # dict, wired by build_model_node through the gateway (the control
        # plane as a reranker); None = logprob scoring only.
        self._verifier_call = None

    async def start(self) -> None:
        self._task = asyncio.create_task(self._drive_loop())
        if self.vision_cfg is not None:
            # Pre-warm the vision-tower jit off the event loop: the first
            # image request otherwise pays the compile (seconds on CPU,
            # minutes through a TPU tunnel) while /health and heartbeats
            # block (round-2 advisor finding, model_node.py:423).
            self._vision_warm = asyncio.create_task(
                asyncio.to_thread(self._warm_vision)
            )

    def _warm_vision(self) -> None:
        import numpy as np

        from agentfield_tpu.models.vision import vision_encode_jit

        S = self.vision_cfg.image_size
        vision_encode_jit(
            self.vision_params, self.vision_cfg, np.zeros((1, S, S, 3), np.float32)
        )

    async def stop(self) -> None:
        if self._task:
            # Re-fire the cancel until the task actually ends: on py3.10 the
            # aio_timeout backport cancels the enclosing task at its
            # deadline, and an EXTERNAL cancel landing in that same window
            # coalesces with it — __aexit__ then relabels the one delivered
            # CancelledError as TimeoutError and the drive loop's idle-wait
            # handler swallows it, leaving the task running forever (a
            # ~1-in-10 teardown hang under load before this loop).
            self._task.cancel()
            while True:
                done, _ = await asyncio.wait({self._task}, timeout=1.0)
                if done:
                    break
                self._task.cancel()
        warm = getattr(self, "_vision_warm", None)
        if warm is not None:
            warm.cancel()
            await asyncio.gather(warm, return_exceptions=True)
        for fut in self._futures.values():
            if not fut.done():
                fut.cancel()
        # Stop the KV offload worker (tiered KV; no-op with the tier off) —
        # a drive loop is gone, so nothing frees pages to demote anyway.
        await asyncio.to_thread(self.engine.close)

    async def _drive_loop(self) -> None:
        """Continuous-batching driver: engine.step() on a worker thread, token
        events dispatched to waiting futures. A step failure must not kill the
        loop silently — it would strand every in-flight future (cf. the
        gateway worker-loop guard)."""
        last_gc = 0.0
        while True:
            if not self.engine.has_work():
                if time.monotonic() - last_gc > 30.0:
                    last_gc = time.monotonic()
                    self.engine.gc_sessions()  # bound idle KV retention
                self._wake.clear()
                try:
                    # wait_for, NOT aio_timeout: the py3.10 backport cancels
                    # the enclosing task at its deadline, so an external
                    # stop() cancel racing the timer would be relabeled
                    # TimeoutError and swallowed by this handler — wait_for
                    # cancels only the inner waiter and always lets an
                    # external CancelledError propagate.
                    await asyncio.wait_for(
                        self._wake.wait(), timeout=self.idle_sleep * 50
                    )
                except (TimeoutError, asyncio.TimeoutError):
                    continue
            try:
                events = await asyncio.to_thread(self.engine.step)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # Fail everything in flight with the real error; the engine's
                # state may be corrupt, so don't pretend those requests live.
                # The flight recorder IS the crash dump (docs/
                # OBSERVABILITY.md): the last ticks before the failure go to
                # the log now, while the evidence is still in the ring.
                from agentfield_tpu.logging import get_logger

                get_logger("model_node").error(
                    "engine step failed; flight recorder dump",
                    error=repr(e),
                    flight_recorder=self.engine.flight.snapshot(last=64),
                )
                for rid, fut in list(self._futures.items()):
                    if not fut.done():
                        fut.set_exception(RuntimeError(f"engine step failed: {e!r}"))
                    self._futures.pop(rid, None)
                    self._buffers.pop(rid, None)
                for rid, q in list(self._streams.items()):
                    self._push_stream(rid, q, _error_event(rid, f"engine step failed: {e!r}"))
                self._streams.clear()
                for g in {id(g): g for g in self._groups.values()}.values():
                    self._fail_group(g, f"engine step failed: {e!r}")
                await asyncio.sleep(0.1)
                continue
            for ev in events:
                group = self._groups.get(ev.request_id)
                if group is not None:
                    # Branch events feed the group lifecycle, never a
                    # client-visible sink directly (the winner replays at
                    # resolution; pruned branches emit nothing).
                    self._on_group_event(group, ev)
                    continue
                stream = self._streams.get(ev.request_id)
                if stream is not None:
                    alive = self._push_stream(ev.request_id, stream, ev)
                    if ev.finished or not alive:
                        self._streams.pop(ev.request_id, None)
                    if alive:
                        continue
                    # fall through: consumer gone, route to the discard path
                if ev.request_id not in self._futures:
                    continue  # cancelled/unknown rid: never recreate buffers
                    # (a setdefault here would leak entries forever)
                if ev.token < 0:
                    # Terminal marker without a token (deadline_exceeded):
                    # resolve with whatever was generated, buffer nothing.
                    self._buffers.setdefault(ev.request_id, [])
                elif not (ev.finished and ev.finish_reason == "stop"):
                    # Stop tokens terminate, they are not content: buffering
                    # one would append EOS text to result["text"] (breaking
                    # e.g. strict parses of constrained scalar outputs).
                    self._buffers.setdefault(ev.request_id, []).append((ev.token, ev.logprob))
                else:
                    self._buffers.setdefault(ev.request_id, [])
                if ev.finished:
                    fut = self._futures.pop(ev.request_id, None)
                    records = self._buffers.pop(ev.request_id, [])
                    if fut is not None and not fut.done():
                        fut.set_result(
                            {
                                "tokens": [t for t, _ in records],
                                "logprobs": [lp for _, lp in records],
                                "finish_reason": ev.finish_reason,
                            }
                        )

    @staticmethod
    def _push_stream(rid: str, q: asyncio.Queue, ev) -> bool:
        """Non-blocking stream dispatch. A full queue means the consumer is
        too slow or gone — drop the stream (returns False) rather than let
        QueueFull kill the drive loop."""
        try:
            q.put_nowait(ev)
            return True
        except asyncio.QueueFull:
            return False

    @staticmethod
    def _schema_key(schema: dict[str, Any]) -> str:
        import json as _json

        return _json.dumps(schema, sort_keys=True)

    def _grammar_for(self, schema: dict[str, Any]):
        """Compile (and cache) the token-level grammar for a JSON schema.
        The cache key is the canonical schema text, so identical schemas from
        different callers share one Grammar and one engine-bank registration.
        Synchronous — async request paths pre-warm via ensure_grammar() so the
        O(vocab × states) compile never blocks the event loop."""
        from agentfield_tpu.serving.grammar import compile_json_schema

        if self.tokenizer is None:
            raise ValueError("constrained decoding needs a tokenizer on this node")
        key = self._schema_key(schema)
        g = self._grammars.get(key)
        if g is None:
            vocab = self.tokenizer.token_bytes(self.cfg.vocab_size)
            g = compile_json_schema(schema, vocab, whitespace=self.grammar_whitespace)
            self._grammars[key] = g
        self._grammars.move_to_end(key)
        while len(self._grammars) > self._grammars_max:
            self._grammars.popitem(last=False)  # LRU out; the engine bank
            # keeps its own strong ref until its rows evict, so in-flight
            # requests are unaffected
        return g

    async def ensure_grammar(self, schema: dict[str, Any]):
        """Pre-compile a schema's grammar OFF the event loop (dedup'd across
        concurrent callers) and RETURN it — callers hand the object to
        _submit directly, so LRU churn between pre-warm and submit can never
        force a synchronous recompile on the event loop."""
        key = self._schema_key(schema)
        g = self._grammars.get(key)
        if g is not None:
            self._grammars.move_to_end(key)
            return g
        fut = self._grammar_futs.get(key)
        if fut is None:
            fut = asyncio.ensure_future(asyncio.to_thread(self._grammar_for, schema))
            self._grammar_futs[key] = fut
            fut.add_done_callback(lambda _f: self._grammar_futs.pop(key, None))
        return await asyncio.shield(fut)

    async def ensure_images(self, prompt: str, images: list) -> tuple[list[int], list]:
        return await self.ensure_media(prompt, images, None)

    async def ensure_media(
        self, prompt: str, images: list | None, audios: list | None
    ) -> tuple[list[int], list]:
        """Run media decode + tower encoding OFF the event loop (mirrors
        ensure_grammar): PIL/WAV decode plus a jitted tower forward — a
        compile on first use — must not block heartbeats and /health. Returns
        the (tokens, mm_embeds) pair _submit accepts as ``prefused``."""
        return await asyncio.to_thread(self._fuse_media, prompt, images, audios)

    def _synthesize_wav_b64(self, text: str) -> tuple[str, int]:
        """Text → (WAV base64, truncated-byte count) through the TTS head;
        the jitted synth runs on a worker thread (asyncio.to_thread at the
        call sites). Text beyond the head's static max_chars budget is
        dropped — reported so callers see the speech/text mismatch (mirrors
        truncated_prompt_tokens)."""
        import base64

        import numpy as np

        from agentfield_tpu.models.audio import (
            float_to_wav,
            tts_synthesize_jit,
        )

        if self.tts_cfg is None:
            raise ValueError(
                "this model node has no TTS head (audio output unsupported); "
                "start it with tts=<config> to serve output='audio'/'speech'"
            )
        cfg = self.tts_cfg
        ids, n_bytes, truncated = _prompt_byte_ids(text, cfg.max_chars)
        wav = np.asarray(tts_synthesize_jit(self.tts_params, cfg, ids)[0], np.float32)
        # trim the static budget to the speakable span of THIS text
        n = max(1, n_bytes) * cfg.frames_per_char * cfg.samples_per_frame
        return base64.b64encode(float_to_wav(wav[:n], cfg.sample_rate)).decode(), truncated

    def _render_png_b64(self, text: str) -> tuple[str, int]:
        """Prompt → (PNG base64, truncated-byte count) through the
        image-generation head; jitted synth runs on a worker thread
        (asyncio.to_thread at the call site). Truncation is reported like
        the TTS path's tts_truncated_chars, never silent."""
        import base64

        import numpy as np

        from agentfield_tpu.models.image_gen import (
            image_to_png,
            imagegen_synthesize_jit,
        )

        cfg = self.imagegen_cfg
        ids, _, truncated = _prompt_byte_ids(text, cfg.max_chars)
        img = imagegen_synthesize_jit(self.imagegen_params, cfg, ids)[0]
        png = base64.b64encode(image_to_png(np.asarray(img))).decode()
        return png, truncated

    def _decode_image(self, item) -> "np.ndarray":
        """One wire image → [S, S, 3] float32 in [0, 1]. Accepts raw encoded
        bytes (the gRPC proto form), {"b64": <base64 PNG/JPEG>} (the HTTP/SDK
        wire form), or a nested list / array of pixels in [0, 1] (tests,
        pre-decoded callers; out-of-range values clip)."""
        import numpy as np

        S = self.vision_cfg.image_size
        raw = None
        if isinstance(item, (bytes, bytearray)):
            raw = bytes(item)
        elif isinstance(item, dict) and "b64" in item:
            import base64

            raw = base64.b64decode(item["b64"])
        if raw is not None:
            import io

            from PIL import Image

            img = Image.open(io.BytesIO(raw)).convert("RGB").resize((S, S))
            return np.asarray(img, np.float32) / 255.0
        arr = np.asarray(item, np.float32)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            raise ValueError(f"image array must be [H, W, 3], got {arr.shape}")
        arr = np.clip(arr, 0.0, 1.0)
        if arr.shape[0] != S or arr.shape[1] != S:
            from PIL import Image

            img = Image.fromarray((arr * 255).astype("uint8")).resize((S, S))
            arr = np.asarray(img, np.float32) / 255.0
        return arr

    def _decode_audio(self, item) -> "np.ndarray":
        """One wire audio part → [max_samples] float32 in [-1, 1]. Accepts
        raw WAV bytes (gRPC proto form), {"b64": <base64 WAV>} (HTTP/SDK wire
        form), or a float array of samples (tests, pre-decoded callers)."""
        import numpy as np

        from agentfield_tpu.models.audio import wav_to_float

        cfg = self.audio_cfg
        raw = None
        if isinstance(item, (bytes, bytearray)):
            raw = bytes(item)
        elif isinstance(item, dict) and "b64" in item:
            import base64

            raw = base64.b64decode(item["b64"])
        if raw is not None:
            return wav_to_float(raw, cfg.sample_rate, cfg.max_samples)
        x = np.asarray(item, np.float32).reshape(-1)
        out = np.zeros((cfg.max_samples,), np.float32)
        n = min(len(x), cfg.max_samples)
        out[:n] = np.clip(x[:n], -1.0, 1.0)
        return out

    def _fuse_images(self, prompt: str, images: list) -> tuple[list[int], list]:
        return self._fuse_media(prompt, images, None)

    def _fuse_media(
        self, prompt: str, images: list | None, audios: list | None
    ) -> tuple[list[int], list]:
        """Tokenize a prompt with ``<image>``/``<audio>`` markers, encoding
        each part through its tower and splicing placeholder tokens +
        embedding spans at the marker positions (LLaVA-style early fusion).
        The engine's mm_embeds seam is modality-agnostic, so image patch
        embeddings and audio frame embeddings ride the same injection path.
        Returns (tokens, mm_embeds for the engine)."""
        import re

        import numpy as np

        images, audios = images or [], audios or []
        if images and self.vision_cfg is None:
            raise ValueError(
                "this model node has no vision tower (images unsupported); "
                "start it with vision=<config> to serve image inputs"
            )
        if audios and self.audio_cfg is None:
            raise ValueError(
                "this model node has no audio tower (audio inputs "
                "unsupported); start it with audio=<config> to serve them"
            )
        if self.tokenizer is None:
            raise ValueError("multimodal inputs need a tokenizer (text prompt)")
        pieces = re.split(r"(<image>|<audio>)", prompt)
        n_img = sum(1 for p in pieces if p == "<image>")
        n_aud = sum(1 for p in pieces if p == "<audio>")
        if n_img != len(images) or n_aud != len(audios):
            raise ValueError(
                f"prompt has {n_img} <image> + {n_aud} <audio> markers for "
                f"{len(images)} images + {len(audios)} audio parts"
            )
        img_embs = aud_embs = None
        if images:
            from agentfield_tpu.models.vision import vision_encode_jit

            batch = np.stack([self._decode_image(im) for im in images])
            img_embs = np.asarray(
                vision_encode_jit(self.vision_params, self.vision_cfg, batch),
                np.float32,
            )  # [N, patches, D]
        if audios:
            from agentfield_tpu.models.audio import audio_encode_jit

            batch = np.stack([self._decode_audio(a) for a in audios])
            aud_embs = np.asarray(
                audio_encode_jit(self.audio_params, self.audio_cfg, batch),
                np.float32,
            )  # [N, frames, D]
        tokens: list[int] = []
        mm: list[tuple[int, Any]] = []
        it_img = iter(range(len(images)))
        it_aud = iter(range(len(audios)))
        for piece in pieces:
            if piece == "<image>":
                emb = img_embs[next(it_img)]
            elif piece == "<audio>":
                emb = aud_embs[next(it_aud)]
            else:
                if piece:
                    tokens.extend(self.tokenizer.encode(piece))
                continue
            mm.append((len(tokens), emb))
            tokens.extend([0] * emb.shape[0])
        return tokens, mm

    def _submit(
        self,
        prompt: str | None,
        tokens: list[int] | None,
        max_new_tokens: int,
        temperature: float,
        top_k: int,
        top_p: float,
        stop_token_ids: list[int] | None,
        register,  # rid -> None; registers the completion sink before submit
        unregister,  # rid -> None; rollback on submit failure
        session_id: str | None = None,
        response_schema: dict[str, Any] | None = None,
        context_overflow: str = "error",
        grammar_obj=None,  # pre-compiled Grammar from ensure_grammar()
        images: list | None = None,
        audios: list | None = None,
        prefused: tuple | None = None,  # (tokens, mm_embeds) from ensure_media()
        deadline_s: float | None = None,  # per-request wall-clock budget
        # (engine-enforced; finish_reason="deadline_exceeded" on expiry —
        # pending work past its deadline is shed without ever admitting)
        priority: int = 0,  # admission priority (overload control): higher
        # admits first within the engine's fairness window; a starved
        # higher-priority request may preempt a lower-priority slot
        # (docs/FAULT_TOLERANCE.md)
        n_branches: int = 1,  # branch decoding (test-time scaling): fork
        # this many KV-shared branches at prefill completion; the CALLER
        # (generate/submit_stream) owns the BranchGroup that scores and
        # prunes them (docs/PREFIX_CACHING.md "Fork / COW branches")
        trace: dict | None = None,  # validated TraceContext (or None): the
        # engine records lifecycle spans against its trace_id
        # (docs/OBSERVABILITY.md); collected at terminal by
        # collect_trace_spans
        handoff_export: bool = False,  # disaggregated pools, phase 1: the
        # engine prefills, publishes the prompt's pages, stashes the tail
        # page + first sampled token, and terminates with
        # finish_reason="handoff" instead of decoding
        handoff: dict | None = None,  # disaggregated pools, phase 2: the
        # prefill node's handoff descriptor — admission live-installs the
        # adopted pages + stashed tail and resumes decoding token-exact
        expect_followup: bool = False,  # agent-aware serving: a follow-up on
        # this session is expected (declared or gateway-inferred) — the
        # engine pins the session's KV warm after this request finishes
        # (docs/OPERATIONS.md "Agent-aware serving")
        followup_candidates: list | None = None,  # candidate next-step
        # suffixes (strings or token lists) a reasoner declared: the engine
        # speculatively prefills each over the retained session in idle
        # budget; a hint only — invalid entries are dropped, never errors
    ) -> tuple[str, int]:
        """Shared tokenize/validate/submit path for both completion styles.

        context_overflow: what to do when prompt + max_new_tokens exceeds the
        engine's context budget — "error" raises RequestTooLongError;
        "truncate_left" keeps the most recent tokens that fit (the TPU-native
        analogue of the reference's token-aware oldest-first trimming,
        agent_ai.py:262-325)."""
        if self._draining:
            raise NodeDrainingError(
                "node is draining (rolling restart): not admitting new work"
            )
        mm_embeds = None
        if images or audios:
            if tokens is not None:
                raise ValueError("media inputs require a text 'prompt', not 'tokens'")
            if prompt is None:
                raise ValueError("media inputs require a text 'prompt'")
            # async callers pre-fuse off-loop via ensure_media(); the
            # synchronous fallback keeps direct/test callers working
            tokens, mm_embeds = prefused if prefused is not None else self._fuse_media(
                prompt, images, audios
            )
        elif tokens is None:
            if prompt is None:
                raise ValueError("one of 'prompt' or 'tokens' is required")
            if self.tokenizer is None:
                raise ValueError("no tokenizer loaded on this model node; pass 'tokens'")
            tokens = self.tokenizer.encode(prompt)
        if context_overflow not in ("error", "truncate_left"):
            raise ValueError(f"unknown context_overflow policy {context_overflow!r}")
        truncated = 0
        if mm_embeds and context_overflow == "truncate_left":
            # Left-truncation would sever media spans / shift their offsets;
            # an over-budget multimodal prompt is a hard error instead.
            budget = self.engine.ecfg.max_context - max_new_tokens
            if len(tokens) > budget:
                raise RequestTooLongError(
                    f"multimodal prompt ({len(tokens)} tokens incl. media "
                    f"embeddings) exceeds the {budget}-token budget and "
                    "cannot be truncated"
                )
        elif context_overflow == "truncate_left":
            budget = self.engine.ecfg.max_context - max_new_tokens
            if budget < 1:
                raise ValueError(
                    f"max_new_tokens={max_new_tokens} leaves no room for a "
                    f"prompt in a {self.engine.ecfg.max_context}-token context"
                )
            if len(tokens) > budget:
                # Keep the tail: the most recent turns matter most, matching
                # the reference's drop-oldest trim. Truncation invalidates
                # session-prefix reuse for this call (different prefix), so
                # the engine simply treats it as a fresh prompt.
                truncated = len(tokens) - budget
                tokens = tokens[-budget:]
        grammar = grammar_obj
        if response_schema is not None:
            if grammar is None:
                grammar = self._grammar_for(response_schema)
            if not stop_token_ids:
                eos = getattr(self.tokenizer, "eos_token_id", None)
                if eos is None:
                    raise ValueError(
                        "constrained decoding needs stop_token_ids (tokenizer "
                        "has no eos_token_id)"
                    )
                stop_token_ids = [eos]
        if isinstance(priority, bool) or not isinstance(priority, int):
            raise ValueError(f"priority must be an integer, got {priority!r}")
        cand_tokens = self._followup_cand_tokens(
            followup_candidates if expect_followup else None
        )
        self._next += 1
        rid = f"gen_{self._next}"
        register(rid)
        try:
            self.engine.submit(
                Request(
                    id=rid,
                    prompt=list(tokens),
                    sampling=SamplingParams(
                        temperature=temperature,
                        top_k=top_k,
                        top_p=top_p,
                        max_new_tokens=max_new_tokens,
                        stop_token_ids=tuple(stop_token_ids or ()),
                    ),
                    session_id=session_id,
                    grammar=grammar,
                    mm_embeds=mm_embeds,
                    deadline_s=deadline_s,
                    priority=priority,
                    n_branches=n_branches,
                    trace=trace,
                    handoff_export=handoff_export,
                    handoff=handoff,
                    expect_followup=bool(expect_followup),
                    followup_candidates=cand_tokens,
                )
            )
        except Exception:
            unregister(rid)
            raise
        self._wake.set()
        return rid, truncated

    def _followup_cand_tokens(self, cands) -> list[list[int]] | None:
        """Normalize declared follow-up candidates (agent-aware serving)
        into token lists for the engine's speculative prefill. A HINT, so
        degradation beats rejection: no tokenizer for a string candidate,
        an empty candidate, or a non-list container → the candidate (or all
        of them) is dropped and the request proceeds keep-warm-only.
        Malformed ELEMENTS inside a declared list still raise — a caller
        that got the type wrong should hear it, same contract as tokens."""
        if not cands:
            return None
        if not isinstance(cands, (list, tuple)):
            raise ValueError(
                f"followup_candidates must be a list, got {type(cands).__name__}"
            )
        cap = max(0, self.engine.ecfg.spec_max_candidates)
        out: list[list[int]] = []
        for cand in cands:
            if len(out) >= cap:
                break  # over-declared: the engine would drop them anyway
            if isinstance(cand, str):
                if self.tokenizer is None:
                    continue  # cannot tokenize: keep-warm only for this one
                toks = self.tokenizer.encode(cand)
            elif isinstance(cand, (list, tuple)):
                toks = list(cand)
                if not all(
                    isinstance(t, int) and not isinstance(t, bool) for t in toks
                ):
                    raise ValueError(
                        "followup_candidates token lists must contain only ints"
                    )
            else:
                raise ValueError(
                    "each followup candidate must be a string or a token list, "
                    f"got {type(cand).__name__}"
                )
            if toks:
                out.append(toks)
        return out or None

    def apply_chat_template(self, messages: list[dict]) -> str:
        """[{role, content}] → one prompt string. HF tokenizers use their
        checkpoint's own chat template (add_generation_prompt=True — the
        reference's CompleteWithMessages rides the provider's template,
        sdk/go/ai/client.go:61); tokenizers without one fall back to a plain
        role-tagged transcript. Media markers inside message content flow
        through to the normal fusion path."""
        for i, m in enumerate(messages):
            bad = (
                not isinstance(m, dict)
                or not isinstance(m.get("content"), str)
                or m.get("role") not in ("system", "user", "assistant")
            )
            if bad:
                raise ValueError(
                    f"messages[{i}] must be {{role: system|user|assistant, "
                    "content: str}"
                )
        tok = getattr(self.tokenizer, "_tok", None)
        if tok is not None and getattr(tok, "chat_template", None):
            return tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        lines = [f"{m['role']}: {m['content']}" for m in messages]
        return "\n".join(lines) + "\nassistant:"

    async def embed(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        pooling: str = "mean",
        context_overflow: str = "error",
        prompts: list[str] | None = None,  # BATCH form: one [B, bucket]
        # forward for the whole list (RAG indexing throughput) — returns
        # {"embeddings": [...], ...} instead of "embedding"
    ) -> dict[str, Any]:
        """Text → L2-normalized embedding from the LM's final-norm hidden
        states (mean or last-token pooled over the REAL tokens; inputs pad
        to the engine's prefill buckets so compile shapes stay bounded).
        The reference has no in-cluster embeddings at all — its memory
        vector API expects caller-supplied vectors from provider embedding
        APIs; here vector memory can be fed entirely in-tree
        (vector_set(embed(text)) → vector_search). Over-long inputs honor
        generate()'s context_overflow contract: "error" (default) rejects,
        "truncate_left" keeps the most recent context and reports
        truncated_tokens."""
        import numpy as _np
        import jax.numpy as _jnp

        from agentfield_tpu.models import llama as _llama

        if pooling not in ("mean", "last"):
            raise ValueError(f"pooling={pooling!r} must be 'mean' or 'last'")
        if context_overflow not in ("error", "truncate_left"):
            raise ValueError(
                f"context_overflow={context_overflow!r} must be 'error' or "
                "'truncate_left'"
            )
        batch_mode = prompts is not None
        if batch_mode:
            if prompt is not None or tokens is not None:
                raise ValueError("prompts is exclusive with prompt/tokens")
            if not prompts:
                raise ValueError("prompts must be non-empty")
            if self.tokenizer is None:
                raise ValueError("no tokenizer loaded on this model node")
            token_rows = [self.tokenizer.encode(p) for p in prompts]
        else:
            if tokens is None:
                if prompt is None:
                    raise ValueError("one of 'prompt', 'tokens', 'prompts' is required")
                if self.tokenizer is None:
                    raise ValueError("no tokenizer loaded on this model node; pass 'tokens'")
                tokens = self.tokenizer.encode(prompt)
            token_rows = [tokens]
        max_ctx = self.engine.ecfg.max_context
        truncated_rows: list[int] = []
        lens: list[int] = []
        for i, row in enumerate(token_rows):
            if not row:
                raise ValueError(f"cannot embed an empty sequence (row {i})")
            if len(row) > max_ctx:
                if context_overflow == "error":
                    raise ValueError(
                        f"sequence of {len(row)} tokens (row {i}) exceeds "
                        f"max_context={max_ctx}; pass context_overflow="
                        "'truncate_left' to embed the most recent context"
                    )
                truncated_rows.append(len(row) - max_ctx)
                token_rows[i] = row = row[-max_ctx:]
            else:
                truncated_rows.append(0)
            lens.append(len(row))
        # bucketed shape: ONE compile per (B, bucket), like engine prefills
        bucket = self.engine.ecfg.prefill_bucket(max(lens))
        B = len(token_rows)
        padded = [[0] * bucket for _ in range(B)]
        for i, row in enumerate(token_rows):
            padded[i][: lens[i]] = row

        def _run():
            toks = _jnp.asarray(padded, _jnp.int32)
            pos = _jnp.arange(bucket, dtype=_jnp.int32)[None].repeat(B, 0)
            nv = _jnp.asarray(lens, _jnp.int32)
            h, _ = _llama.forward(
                self.engine.params, self.cfg, toks, pos,
                collect_kv=False, return_hidden=True,
            )  # [B, bucket, D]
            real = (_jnp.arange(bucket)[None, :] < nv[:, None])[..., None]
            if pooling == "mean":
                v = _jnp.sum(
                    _jnp.where(real, h.astype(_jnp.float32), 0.0), axis=1
                ) / nv[:, None]
            else:
                v = _jnp.take_along_axis(
                    h.astype(_jnp.float32), (nv - 1)[:, None, None], axis=1
                )[:, 0]
            return v / _jnp.maximum(
                _jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-9
            )

        vecs = await asyncio.to_thread(lambda: _np.asarray(_run()))
        base = {"dim": int(vecs.shape[1]), "model": self.model_name, "pooling": pooling}
        if batch_mode:
            out = {
                **base,
                "embeddings": vecs.tolist(),
                "tokens_used": lens,
            }
            if any(truncated_rows):
                out["truncated_tokens"] = truncated_rows
            return out
        out = {**base, "embedding": vecs[0].tolist(), "tokens_used": lens[0]}
        if truncated_rows[0]:
            out["truncated_tokens"] = truncated_rows[0]
        return out

    # -- cluster prefix tier (docs/PREFIX_CACHING.md "Cluster tier") ----

    async def kv_export_pages(
        self, chains_hex: list[str], max_bytes: int, handoff: str | None = None
    ) -> list[tuple[dict, bytes]]:
        """Serve a peer's kv_fetch: look the requested chain hashes up in
        this engine's prefix index (both tiers) and serialize each page as
        ``(meta, payload)`` — meta describes the flattened payload leaves
        (dtype/shape/byte segments; a quantized pool ships values AND
        scales), payload is the raw concatenated bytes the channel carries
        as a BINARY frame (no base64: the old text encoding paid ~33% wire
        overhead on every transferred page). The device→host copies run
        off the event loop; the byte cap stops serialization early (the
        requester re-prefills the tail).

        With ``handoff`` (disaggregated pools, phase 2 pulling its live
        handoff), the stashed tail page for that handoff id is serialized
        FIRST — its meta carries ``handoff`` instead of ``chain`` so the
        requester's chain matching never confuses it with an indexed page
        — and the stash entry is consumed (one-shot)."""
        import jax
        import numpy as np

        chains = []
        for c in chains_hex:
            try:
                b = bytes.fromhex(c)
            except ValueError:
                continue
            if len(b) == 16:
                chains.append(b)
        # what a dense (bf16/f32) page would put on the wire — the yardstick
        # for the kv_quant wire-saving counter
        dense_page = self.engine.kv_page_bytes_dense
        quant_on = self.engine.ecfg.kv_quant_dtype != "none"

        def _export_and_serialize():
            # ONE thread hop covers both the D2H copies and the payload
            # flattening of up-to-MBs — serializing on the event loop
            # would stall every stream multiplexed on this node.
            raw = self.engine.export_kv_pages(chains)
            pages: list[tuple[dict, bytes]] = []
            total = wire_saved = handoff_bytes = 0
            tail = (
                self.engine.export_handoff_tail(handoff) if handoff else None
            )
            if tail is not None:
                # Tail page ahead of the chain pages: the byte cap must
                # never starve the one payload phase 2 cannot re-derive
                # from the published index.
                _desc, t_payload = tail
                t_leaves = [
                    np.ascontiguousarray(np.asarray(a))
                    for a in jax.tree.leaves(t_payload)
                ]
                t_blobs = [a.tobytes() for a in t_leaves]
                sz = sum(len(b) for b in t_blobs)
                if sz <= max_bytes:
                    pages.append(
                        (
                            {
                                "handoff": handoff,
                                "parts": [
                                    {"dtype": str(a.dtype), "shape": list(a.shape)}
                                    for a in t_leaves
                                ],
                                "segs": [len(b) for b in t_blobs],
                            },
                            b"".join(t_blobs),
                        )
                    )
                    total += sz
                    handoff_bytes = sz
            for chain, depth, payload in raw:
                leaves = [
                    np.ascontiguousarray(np.asarray(a))
                    for a in jax.tree.leaves(payload)
                ]
                blobs = [a.tobytes() for a in leaves]
                sz = sum(len(b) for b in blobs)
                if total + sz > max_bytes:
                    break
                meta = {
                    "chain": chain.hex(),
                    "depth": int(depth),
                    "parts": [
                        {"dtype": str(a.dtype), "shape": list(a.shape)}
                        for a in leaves
                    ],
                    "segs": [len(b) for b in blobs],
                }
                pages.append((meta, b"".join(blobs)))
                total += sz
                if quant_on:
                    wire_saved += max(0, dense_page - sz)
            return pages, total, wire_saved, handoff_bytes

        pages, total, wire_saved, handoff_bytes = await asyncio.to_thread(
            _export_and_serialize
        )
        self.engine.stats["kv_fetch_served_total"] += len(pages)
        self.engine.stats["kv_fetch_bytes_total"] += total
        if wire_saved:
            self.engine.stats["kv_quant_wire_bytes_saved_total"] += wire_saved
        if handoff_bytes:
            self.engine.stats["kv_handoff_bytes_total"] += handoff_bytes
        return pages

    async def maybe_prefetch_kv(self, tokens: list[int] | None, hint: Any) -> int:
        """Best-effort pull of this prompt's missing prefix pages from the
        peer the gateway's affinity scorer named (the ``kv_peer`` hint in
        the generate input). Fetched pages land in the pool's host store
        (adopt) and restore at admission through PR 8's machinery — so
        EVERY failure mode (no channel, peer gone, timeout, malformed
        payload, seeded kv.fetch_fail/kv.fetch_stall) degrades to an
        ordinary local prefill, token-exact, zero pages leaked. Returns the
        number of pages adopted.

        Disaggregated pools: when the hint carries a ``handoff`` id, the
        same fetch also pulls the prefill node's stashed tail page (last
        partial page + first sampled token's KV) and stashes it for
        admission's live-slot install — so phase 2 resumes with ZERO
        prefill work. A missing/torn tail only costs the live install:
        admission falls back to prefilling from the adopted prefix."""
        import numpy as np

        from agentfield_tpu.prefix_hash import page_chain_hashes

        if (
            not self.kv_fetch_enabled
            or self._kv_fetch_fn is None
            or not isinstance(hint, dict)
            or not tokens
            or len(tokens) < 2
        ):
            return 0
        peer = hint.get("node_id")
        ps = self.engine.ecfg.page_size
        if not isinstance(peer, str) or hint.get("page_size") != ps:
            return 0  # mismatched page geometry: chains can never align
        hid = hint.get("handoff")
        if not isinstance(hid, str):
            hid = None  # plain prefix prefetch (no live-slot tail)
        matchable = list(tokens[: len(tokens) - 1])
        hashes = page_chain_hashes(matchable, ps)
        local_pages = self.engine.peek_prefix(matchable) // ps
        want = int(hint.get("pages") or len(hashes))
        missing = hashes[local_pages : min(want, len(hashes))]
        if not missing and hid is None:
            return 0
        # A handoff pull is unique to its id (the serving stash is one-shot),
        # so it never shares a leader with a plain same-prefix burst-mate.
        key = (peer, ("handoff", hid) if hid is not None else missing[0])
        leader = self._kv_prefetch_inflight.get(key)
        if leader is not None:
            # A same-prefix burst-mate is already pulling this range: wait
            # for its adoption instead of issuing a duplicate transfer
            # (shielded — a cancelled follower must not kill the leader's
            # completion signal). Admission's lookup finds whatever the
            # leader adopted; if it failed, this request just re-prefills.
            await asyncio.shield(leader)
            return 0
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._kv_prefetch_inflight[key] = fut
        try:
            self.engine.stats["kv_fetch_requested_total"] += 1
            if hid is not None:
                got = await self._kv_fetch_fn(
                    peer, [h.hex() for h in missing], self.kv_fetch_timeout_s,
                    handoff=hid,
                )
            else:
                # keyword omitted on the plain path: test doubles (and any
                # older transport) keep the 3-arg signature
                got = await self._kv_fetch_fn(
                    peer, [h.hex() for h in missing], self.kv_fetch_timeout_s
                )
            if not got:
                self.engine.stats["kv_fetch_failed_total"] += 1
                return 0

            def _decode_entries():
                # frombuffer over up to MBs of payload: off the event
                # loop, or every stream multiplexed on this node stalls
                # while one transfer decodes.
                by_chain = {
                    pg.get("chain"): pg for pg in got if isinstance(pg, dict)
                }
                # per-leaf (dtype, shape) contract of ONE page payload —
                # validated against THIS engine's pool geometry (incl. the
                # quantized value/scale leaves), so a mismatched or corrupt
                # peer can only end the adoptable prefix early
                spec = self.engine.page_payload_spec()

                def _leaves_of(pg: dict) -> list | None:
                    # one page payload, validated leaf-by-leaf against THIS
                    # pool's geometry (shared by chain pages and the tail)
                    parts = pg["parts"]
                    segs = [int(s) for s in pg["segs"]]
                    data = pg["data"]
                    if len(parts) != len(spec) or len(segs) != len(spec):
                        raise ValueError("payload leaf count mismatch")
                    leaves = []
                    off = 0
                    for part, seg, (want_dt, want_shape) in zip(parts, segs, spec):
                        dt = np.dtype(part["dtype"])
                        shape = tuple(part["shape"])
                        if (str(dt), shape) != (want_dt, want_shape):
                            raise ValueError(
                                f"leaf {part} != expected {(want_dt, want_shape)}"
                            )
                        leaves.append(
                            np.frombuffer(data[off : off + seg], dtype=dt).reshape(
                                shape
                            )
                        )
                        off += seg
                    return leaves

                tail_payload = None
                if hid is not None:
                    tpg = next(
                        (
                            pg
                            for pg in got
                            if isinstance(pg, dict) and pg.get("handoff") == hid
                        ),
                        None,
                    )
                    if tpg is not None:
                        try:
                            tail_payload = self.engine.build_page_payload(
                                _leaves_of(tpg)
                            )
                        except Exception:
                            tail_payload = None  # admission counts the
                            # failed handoff when the stash comes up empty
                out = []
                for idx, h in enumerate(missing):
                    pg = by_chain.get(h.hex())
                    if pg is None:
                        break  # a gap ends the adoptable prefix (chain rule)
                    try:
                        payload = self.engine.build_page_payload(_leaves_of(pg))
                    except Exception:
                        self.engine.stats["kv_fetch_failed_total"] += 1
                        break
                    depth = local_pages + idx
                    out.append(
                        (h, depth,
                         tuple(matchable[depth * ps : (depth + 1) * ps]),
                         payload)
                    )
                return out, tail_payload

            entries, tail = await asyncio.to_thread(_decode_entries)
            if hid is not None and tail is not None:
                # Stash the live tail page for admission's live-slot install
                # (engine._try_handoff_install); chain pages adopt below as
                # usual. Order is irrelevant — both sit in host stores until
                # this request is admitted.
                self.engine.adopt_handoff_tail(hid, tail)
            if not entries:
                return 0
            return self.engine.adopt_kv_pages(entries)
        finally:
            self._kv_prefetch_inflight.pop(key, None)
            if not fut.done():
                fut.set_result(None)

    async def generate(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        messages: list[dict] | None = None,
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_token_ids: list[int] | None = None,
        session_id: str | None = None,
        response_schema: dict[str, Any] | None = None,
        context_overflow: str = "error",
        images: list | None = None,
        audios: list | None = None,
        output: str = "text",
        deadline_s: float | None = None,
        priority: int = 0,
        n_branches: int = 1,  # test-time scaling (docs/PREFIX_CACHING.md
        # "Fork / COW branches"): fork the request's KV into this many
        # branches after ONE prefill, decode them as batch-mates, return
        # only the winner (plus a "branches" summary block)
        branch_policy: Any = None,  # "best_of_n" (default) | "beam" | a
        # {"type", "verifier", "beam_width", "beam_interval"} object —
        # branching.validate_branch_spec is the one contract definition
        kv_peer: dict | None = None,  # cluster prefix tier: gateway hint
        # naming the peer node whose sketch advertised this prompt's prefix;
        # missing pages are pulled over the channel before admission
        # (docs/PREFIX_CACHING.md "Cluster tier"). Best-effort: any failure
        # degrades to an ordinary local prefill.
        handoff_export: bool = False,  # disaggregated pools, phase 1
        # (docs/ARCHITECTURE.md "Two-phase dispatch"): prefill + publish
        # pages, return a ``handoff`` descriptor in the result instead of
        # decoding. Best-effort: an ineligible request (grammar, media,
        # branches, tiny prompt) silently decodes here instead.
        handoff: dict | None = None,  # disaggregated pools, phase 2: the
        # phase-1 descriptor; paired with a kv_peer hint carrying the same
        # handoff id so the tail page rides the prefetch. Any failure
        # degrades to a local (re-)prefill — token-exact under greedy.
        trace: dict | None = None,  # request-scoped tracing
        # (docs/OBSERVABILITY.md): the gateway's TraceContext — engine
        # lifecycle spans are recorded against its trace_id and shipped
        # back in ``result["trace"]`` (the gateway pops the key before the
        # result is persisted). Absent/invalid → no spans, no result key.
        expect_followup: bool = False,  # agent-aware serving: pin this
        # request's session warm after it finishes — a follow-up is coming
        # (docs/OPERATIONS.md "Agent-aware serving")
        followup_candidates: list | None = None,  # candidate next-step
        # suffixes (strings or token lists) to speculatively prefill over
        # the retained session in idle budget; requires expect_followup
    ) -> dict[str, Any]:
        if output not in ("text", "audio", "speech", "image"):
            raise ValueError(
                f"unknown output modality {output!r}: 'text' | 'audio' "
                "(synthesize the prompt) | 'speech' (generate, then "
                "synthesize the generated text) | 'image' (render the prompt)"
            )
        n_branches, branch_policy = validate_branch_spec(n_branches, branch_policy)
        if n_branches > 1:
            if output != "text":
                raise ValueError("branch decoding (n_branches > 1) is text-only")
            if response_schema is not None:
                raise ValueError(
                    "branch decoding is incompatible with response_schema "
                    "(constrained decoding owns the sampler mask)"
                )
            if images or audios:
                raise ValueError("branch decoding does not take media inputs")
        if messages is not None:
            if prompt is not None or tokens is not None:
                raise ValueError("messages is exclusive with prompt/tokens")
            prompt = self.apply_chat_template(messages)
        if output in ("audio", "speech") and self.tts_cfg is None:
            # Fail in milliseconds, not after a full LM decode.
            raise ValueError(
                "this model node has no TTS head (audio output unsupported); "
                "start it with tts=<config> to serve output='audio'/'speech'"
            )
        if output == "image":
            # Text-to-image (reference: agent_ai.py:1004 forwards the prompt
            # to a provider image API): the prompt itself is rendered.
            if self.imagegen_cfg is None:
                raise ValueError(
                    "this model node has no image-generation head; start it "
                    "with imagegen=<config> to serve output='image'"
                )
            if images or audios:
                raise ValueError(
                    "output='image' renders the prompt — media inputs would "
                    "be silently dropped"
                )
            if not prompt:
                raise ValueError("output='image' requires a text prompt")
            png_b64, img_trunc = await asyncio.to_thread(self._render_png_b64, prompt)
            out = {
                "text": prompt,
                "parts": [{"type": "image", "mime": "image/png", "data_b64": png_b64}],
                "model": self.model_name,
                "finish_reason": "imagegen",
                "tokens": [],
            }
            if img_trunc:
                out["imagegen_truncated_chars"] = img_trunc
            return out
        if output == "speech" and self.tokenizer is None:
            raise ValueError(
                "output='speech' needs a tokenizer on this node (the "
                "generated text is what gets synthesized)"
            )
        if output == "audio":
            # Pure TTS (reference: agent_ai.py:750 hands text to a speech
            # API): no LM decode, the prompt itself is spoken.
            if images or audios:
                raise ValueError(
                    "output='audio' speaks the prompt verbatim — media "
                    "inputs would be silently dropped; use output='speech' "
                    "to understand media and speak the response"
                )
            if not prompt:
                raise ValueError("output='audio' requires a text prompt")
            wav_b64, tts_trunc = await asyncio.to_thread(
                self._synthesize_wav_b64, prompt
            )
            out = {
                "text": prompt,
                "parts": [{"type": "audio", "mime": "audio/wav", "data_b64": wav_b64}],
                "model": self.model_name,
                "finish_reason": "tts",
                "tokens": [],
            }
            if tts_trunc:
                out["tts_truncated_chars"] = tts_trunc
            return out
        trace = tracing.valid_context(trace)
        t0_w, t0_m = time.time(), time.perf_counter()
        grammar_obj = None
        if response_schema is not None:
            grammar_obj = await self.ensure_grammar(response_schema)
        prefused = None
        if (images or audios) and prompt is not None and tokens is None:
            prefused = await self.ensure_media(prompt, images, audios)
        if kv_peer is not None and tokens is not None and not (images or audios):
            await self.maybe_prefetch_kv(tokens, kv_peer)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        group_holder: dict[str, BranchGroup] = {}
        if n_branches > 1:
            def register(r: str) -> None:
                group_holder["g"] = self._register_group(
                    r, n_branches, branch_policy, ("future", fut)
                )

            def unregister(r: str) -> None:
                g = group_holder.get("g")
                if g is not None:
                    self._teardown_group(g)
        else:
            register = lambda r: self._futures.__setitem__(r, fut)  # noqa: E731
            unregister = lambda r: self._futures.pop(r, None)  # noqa: E731
        rid, truncated = self._submit(
            prompt,
            tokens,
            max_new_tokens,
            temperature,
            top_k,
            top_p,
            stop_token_ids,
            register=register,
            unregister=unregister,
            session_id=session_id,
            response_schema=response_schema,
            context_overflow=context_overflow,
            grammar_obj=grammar_obj,
            images=images,
            audios=audios,
            prefused=prefused,
            deadline_s=deadline_s,
            priority=priority,
            n_branches=n_branches,
            trace=trace,
            handoff_export=handoff_export,
            handoff=handoff,
            expect_followup=expect_followup,
            followup_candidates=followup_candidates,
        )
        try:
            result = await fut
        except asyncio.CancelledError:
            # Caller gone (gRPC deadline, disconnect): free the engine slot —
            # decoding for a dead reader wastes TPU steps and pins pages.
            # A branch group cancels its WHOLE fan-out.
            g = group_holder.get("g")
            if g is not None and g.parent in self._group_sinks:
                self._cancel_group(g)
            self._futures.pop(rid, None)
            self._buffers.pop(rid, None)
            self.cancel(rid)
            raise
        if self.tokenizer is not None:
            result["text"] = self.tokenizer.decode(result["tokens"])
        result["model"] = self.model_name
        if truncated:
            result["truncated_prompt_tokens"] = truncated
        if handoff_export and result.get("finish_reason") == "handoff":
            # Phase-1 terminal: the descriptor rides the result back to the
            # gateway, which re-dispatches phase 2 to a decode node. A
            # missing descriptor (stash expired/evicted) leaves the key off
            # — the gateway treats that as an ordinary completed result.
            desc = self.engine.pop_handoff_desc(rid)
            if desc is not None:
                result["handoff"] = desc
        if output == "speech":
            # Speak the GENERATED text (reference chat-audio shape,
            # agent_ai.py:864: text response + audio of that response).
            # An empty generation (immediate EOS) speaks as near-silence —
            # the synth pads to one frame span; not an error.
            wav_b64, tts_trunc = await asyncio.to_thread(
                self._synthesize_wav_b64, result.get("text", "")
            )
            result["parts"] = [
                {"type": "audio", "mime": "audio/wav", "data_b64": wav_b64}
            ]
            if tts_trunc:
                result["tts_truncated_chars"] = tts_trunc
        if trace is not None:
            # Node-side spans ride the result back to the gateway's
            # TraceStore (the gateway pops the key before persisting): the
            # node.generate envelope plus every engine lifecycle span the
            # request recorded. Tracing off → no ctx → no key — the result
            # shape is bit-compatible with today's (pinned).
            _tr = tracing.tracer()
            _tr.record_span(
                "node.generate", trace["trace_id"], t0_w,
                (time.perf_counter() - t0_m) * 1e3,
                {"rid": rid, "finish": result.get("finish_reason")},
            )
            result["trace"] = {
                "trace_id": trace["trace_id"],
                "spans": self.collect_trace_spans(trace),
            }
        return result

    def submit_stream(
        self,
        prompt: str | None = None,
        tokens: list[int] | None = None,
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_token_ids: list[int] | None = None,
        session_id: str | None = None,
        response_schema: dict[str, Any] | None = None,
        context_overflow: str = "error",
        grammar_obj=None,
        images: list | None = None,
        audios: list | None = None,
        prefused: tuple | None = None,
        deadline_s: float | None = None,
        priority: int = 0,
        n_branches: int = 1,
        branch_policy: Any = None,
        trace: dict | None = None,
        handoff: dict | None = None,  # disaggregated pools, phase 2 (a
        # streamed phase-2 resume): see generate(). Phase 1 itself is
        # never streamed — the gateway submits it unary.
        expect_followup: bool = False,  # agent-aware serving keep-warm
        # hint — see generate()
        followup_candidates: list | None = None,  # speculative next-step
        # candidates — see generate()
    ) -> tuple[str, asyncio.Queue, int]:
        """Streaming variant: returns (request_id, queue of TokenEvents,
        truncated_prompt_tokens) — the truncation count rides along so
        streaming transports report the same ``truncated_prompt_tokens`` a
        unary generate() does. Raises QueueFullError / RequestTooLongError
        like generate().

        With ``n_branches > 1`` the stream is GROUP-AWARE: nothing is
        emitted while the branches decode; at resolution the WINNER's
        tokens replay into the queue (then one terminal) — pruned branches
        produce no client-visible frames, and the ``branches`` summary is
        retrievable via :meth:`pop_group_meta` after the terminal."""
        n_branches, branch_policy = validate_branch_spec(n_branches, branch_policy)
        if n_branches > 1:
            if response_schema is not None:
                raise ValueError(
                    "branch decoding is incompatible with response_schema "
                    "(constrained decoding owns the sampler mask)"
                )
            if images or audios:
                raise ValueError("branch decoding does not take media inputs")
        q: asyncio.Queue = asyncio.Queue(maxsize=4096)
        if n_branches > 1:
            register = lambda r: self._register_group(  # noqa: E731
                r, n_branches, branch_policy, ("stream", q)
            )
            unregister = lambda r: self._teardown_group(  # noqa: E731
                self._groups[r]
            ) if r in self._groups else None
        else:
            register = lambda r: self._streams.__setitem__(r, q)  # noqa: E731
            unregister = lambda r: self._streams.pop(r, None)  # noqa: E731
        rid, truncated = self._submit(
            prompt,
            tokens,
            max_new_tokens,
            temperature,
            top_k,
            top_p,
            stop_token_ids,
            register=register,
            unregister=unregister,
            session_id=session_id,
            response_schema=response_schema,
            context_overflow=context_overflow,
            grammar_obj=grammar_obj,
            images=images,
            audios=audios,
            prefused=prefused,
            deadline_s=deadline_s,
            priority=priority,
            n_branches=n_branches,
            trace=tracing.valid_context(trace),
            handoff=handoff,
            expect_followup=expect_followup,
            followup_candidates=followup_candidates,
        )
        return rid, q, truncated

    def collect_trace_spans(self, ctx) -> list[dict]:
        """Pop this trace's spans from the process buffer and stamp each
        with the dispatch labels the gateway put in the TraceContext
        (``node``, ``attempt``) — the waterfall must say WHICH node served
        WHICH attempt, and engine spans cannot know that themselves.
        Called at terminal time by generate() (unary) and by the channel
        server's trace-collect hook (streaming + failure terminals)."""
        ctx = tracing.valid_context(ctx)
        if ctx is None:
            return []
        spans = tracing.tracer().pop(ctx["trace_id"])
        node = ctx.get("node")
        attempt = ctx.get("attempt")
        for s in spans:
            if node is not None:
                s.setdefault("node", node)
            if attempt is not None:
                s.setdefault("attempt", attempt)
        return spans

    def pop_group_meta(self, rid: str) -> dict | None:
        """The ``branches`` summary of a resolved streaming group (set at
        winner replay); one-shot so abandoned streams do not accumulate."""
        return self._group_meta.pop(rid, None)

    async def drain(self, grace_s: float = 30.0) -> dict[str, Any]:
        """Graceful drain (rolling restart): stop admitting, let in-flight
        requests finish; whatever is still running at the grace cutoff is
        deadline-outed (each consumer gets a terminal
        finish_reason="deadline_exceeded" event — never a silent hang).
        Idempotent; returns a summary for the operator log."""
        t0 = time.monotonic()
        first = not self._draining
        self._draining = True
        if first:
            self.engine.stats["drains_total"] += 1
        while self.engine.has_work() and time.monotonic() - t0 < grace_s:
            self._wake.set()  # keep the drive loop stepping
            await asyncio.sleep(0.02)
        cancelled = 0
        if self.engine.has_work():
            cancelled = self.engine.deadline_all_now()
            self.engine.stats["drain_cancelled"] += cancelled
            self._wake.set()
            # deadline-out is one step away; bound the wait anyway
            t1 = time.monotonic()
            while self.engine.has_work() and time.monotonic() - t1 < 10.0:
                self._wake.set()
                await asyncio.sleep(0.02)
        return {
            "drained": not self.engine.has_work(),
            "deadline_outed": cancelled,
            "elapsed_s": round(time.monotonic() - t0, 3),
        }

    def cancel(self, rid: str) -> None:
        """Cancel an in-flight request and wake the drive loop so the slot
        frees now, not at the next natural step. The one cancel recipe for
        every abandoned-caller path (generate() CancelledError, stream
        disconnects)."""
        self.engine.request_cancel(rid)
        self._wake.set()

    def release_stream(self, rid: str) -> None:
        """Consumer gone: stop dispatching to its queue (remaining tokens take
        the discard path). A still-unresolved branch GROUP behind the stream
        is cancelled whole — decoding N branches for a dead reader is N
        slots of waste."""
        self._streams.pop(rid, None)
        g = self._groups.get(rid)
        if g is not None and self._group_sinks.get(g.parent, ("", None))[0] == "stream":
            self._cancel_group(g)
        self._group_meta.pop(rid, None)

    # -- branch decoding (docs/PREFIX_CACHING.md "Fork / COW branches") --

    # Winner-replay stall bound: how long one queue put may wait on a slow
    # stream consumer before the replay declares it dead (seconds).
    _REPLAY_STALL_S = 30.0

    def _register_group(
        self, parent_rid: str, n: int, policy: dict, sink: tuple[str, Any]
    ) -> BranchGroup:
        g = BranchGroup(parent_rid, n, policy)
        for rid in g.branch_rids():
            self._groups[rid] = g
        self._group_sinks[parent_rid] = sink
        return g

    def _teardown_group(self, g: BranchGroup) -> None:
        for rid in [r for r, gg in self._groups.items() if gg is g]:
            del self._groups[rid]
        self._group_sinks.pop(g.parent, None)

    def _cancel_group(self, g: BranchGroup) -> None:
        """Client gone: cancel every LIVE branch through the engine's
        request_cancel path so the whole fan-out's pages free now (finished
        branches already released; pruned ones were already cancelled)."""
        live = [b.rid for b in map(g.branch, g.branch_rids()) if b is not None and b.live]
        self._teardown_group(g)
        for rid in live:
            self.engine.request_cancel(rid)
        self._wake.set()

    def _fail_group(self, g: BranchGroup, error: str) -> None:
        sink = self._group_sinks.get(g.parent)
        self._teardown_group(g)
        if sink is None:
            return
        kind, obj = sink
        if kind == "future":
            if not obj.done():
                obj.set_exception(RuntimeError(error))
        else:
            self._push_stream(g.parent, obj, _error_event(g.parent, error))

    def _on_group_event(self, g: BranchGroup, ev) -> None:
        for act in g.on_event(ev.request_id, ev):
            if act[0] == "cancel":
                # Pruned: pages free immediately; no client-visible frames
                # were ever emitted for this branch.
                self.engine.stats["branch_pruned_total"] += 1
                self.cancel(act[1])
            elif act[0] == "fork":
                _, src, new_rid = act
                self._groups[new_rid] = g
                self.engine.request_fork(src, new_rid)
                self._wake.set()
            elif act[0] == "resolve":
                t = asyncio.create_task(self._resolve_group(g))
                self._group_tasks.add(t)
                t.add_done_callback(self._group_tasks.discard)

    @staticmethod
    def _branch_content(b) -> list[tuple[int, float | None]]:
        """A branch's CONTENT records: the terminal stop token is a
        terminator, not content (same rule as the unary buffering path)."""
        if b.finish_reason == "stop" and b.records:
            return b.records[:-1]
        return list(b.records)

    async def _resolve_group(self, g: BranchGroup) -> None:
        """Every branch settled: pick the winner (cumulative logprob, or
        the policy's verifier reasoner via the control plane) and deliver
        it to the group's one client-visible sink."""
        cands = g.candidates()
        winner = cands[0] if cands else None
        verifier_used = False
        target = g.policy.get("verifier")
        if (
            winner is not None
            and len(cands) > 1
            and target
            and self._verifier_call is not None
            and self.tokenizer is not None
        ):
            # Control-plane reranking: the candidate TEXTS go to the named
            # reasoner through the gateway; its pick overrides the logprob
            # order. Any failure degrades to the logprob winner — a broken
            # verifier must not fail a completed generation.
            self.engine.stats["branch_verifier_calls_total"] += 1
            texts = [
                self.tokenizer.decode([t for t, _ in self._branch_content(b)])
                for b in cands
            ]
            try:
                res = await self._verifier_call(
                    target,
                    {
                        "task": "rerank",
                        "candidates": texts,
                        "scores": [round(b.cum_logprob, 4) for b in cands],
                    },
                )
                idx = self._parse_verdict(res, len(cands))
                if idx is not None:
                    winner = cands[idx]
                    verifier_used = True
            except Exception as e:
                from agentfield_tpu.logging import get_logger

                get_logger("model_node").warning(
                    "branch verifier failed; using logprob winner",
                    target=target, error=repr(e),
                )
        if winner is None:
            winner = g.fallback_branch()
        meta = g.summary(winner, verifier_used)
        # Fetch the sink AFTER the verifier await: a client that
        # disconnected during it already tore the group down
        # (release_stream/_cancel_group) — delivering to the stale sink
        # would strand a _group_meta entry nothing ever pops.
        sink = self._group_sinks.get(g.parent)
        self._teardown_group(g)
        if sink is None or winner is None:
            return
        kind, obj = sink
        if kind == "future":
            content = self._branch_content(winner)
            if not obj.done():
                obj.set_result(
                    {
                        "tokens": [t for t, _ in content],
                        "logprobs": [lp for _, lp in content],
                        "finish_reason": winner.finish_reason,
                        "branches": meta,
                    }
                )
        else:
            # Group-aware streaming: the winner's tokens replay into the
            # client stream only now — pruned/losing branches emitted no
            # client-visible frames at any point.
            self._group_meta[g.parent] = meta
            await self._replay_winner(g.parent, obj, winner)

    @staticmethod
    def _parse_verdict(res, n: int) -> int | None:
        """Accept {"best": i} or {"scores": [...]} shaped verdicts (nested
        under "result" tolerated); anything else → None (logprob wins)."""
        if isinstance(res, dict) and isinstance(res.get("result"), dict):
            res = res["result"]
        if not isinstance(res, dict):
            return None
        best = res.get("best")
        if isinstance(best, bool):
            return None
        if isinstance(best, int) and 0 <= best < n:
            return best
        scores = res.get("scores")
        if (
            isinstance(scores, list)
            and len(scores) == n
            and all(isinstance(s, (int, float)) and not isinstance(s, bool) for s in scores)
        ):
            return max(range(n), key=lambda i: scores[i])
        return None

    async def _replay_winner(self, parent_rid: str, q: asyncio.Queue, b) -> None:
        """Synthesize the winner's TokenEvents (re-labeled under the parent
        rid) into the stream queue, ending with exactly one terminal.
        Replay is CLIENT-PACED: a winner longer than the queue's capacity
        awaits the consumer instead of tripping QueueFull (which would drop
        the terminal and wedge the stream); a consumer that stops draining
        for ``_REPLAY_STALL_S`` is treated as gone and the rest drops."""
        from agentfield_tpu.serving.engine import TokenEvent

        async def push(ev) -> bool:
            try:
                q.put_nowait(ev)
                return True
            except asyncio.QueueFull:
                try:
                    async with aio_timeout(self._REPLAY_STALL_S):
                        await q.put(ev)
                    return True
                except TimeoutError:
                    return False  # consumer dead: drop the rest

        records = list(b.records)
        reason = b.finish_reason
        tokened_terminal = reason in ("stop", "length") and bool(records)
        for i, (tok, lp) in enumerate(records):
            last = i == len(records) - 1
            ev = TokenEvent(
                request_id=parent_rid,
                token=tok,
                index=i,
                finished=last and tokened_terminal,
                finish_reason=reason if last and tokened_terminal else None,
                logprob=lp,
            )
            if not await push(ev):
                return
        if not tokened_terminal:
            # deadline/error terminals carry no token (engine convention)
            await push(
                TokenEvent(
                    request_id=parent_rid, token=-1, index=-1, finished=True,
                    finish_reason=reason or "error: branch group unresolved",
                )
            )


def build_model_node(
    node_id: str = "model",
    control_plane: str | None = None,
    model: str = "llama-tiny",
    params: Any = None,
    ecfg: EngineConfig | None = None,
    tokenizer=None,
    seed: int = 0,
    checkpoint: str | None = None,
    tp: int = 1,
    vision=None,  # vision tower config name/VisionConfig/(cfg, params) —
    # enables image inputs on this node (ModelBackend vision contract)
    grammar_whitespace: bool = False,
    audio=None,  # audio input tower (ModelBackend audio contract)
    tts=None,  # audio output head (ModelBackend tts contract)
    imagegen=None,  # image output head (ModelBackend imagegen contract)
    quant: str | None = None,  # "int8" → weight-only quantized serving
    # (models/quant.py): halves decode-step HBM weight traffic
    spec_draft: str | None = None,  # draft model preset for speculative
    # decoding (requires ecfg.spec_k > 0 or spec_k below)
    spec_k: int | None = None,  # proposals per step; sets ecfg.spec_k
    lora: str | None = None,  # LoRA adapter dir (training.lora.save_adapter):
    # merged into the base weights at load — fine-tune → merge → serve
    role: str | None = None,  # disaggregated pools (docs/OPERATIONS.md
    # "Disaggregated pools"): "prefill" | "decode" | "mixed". Default is
    # the AGENTFIELD_NODE_ROLE env knob, else "mixed" — which keeps the
    # gateway's dispatch bit-compatible with a role-less fleet (pinned).
) -> tuple[Agent, ModelBackend]:
    """Construct (agent, backend): the agent exposes `generate` and handles
    registration/heartbeats; the backend drives the engine. Caller sequence:
    ``await backend.start(); await agent.start()``. With `checkpoint`, weights
    (and config + tokenizer when present) come from a HF checkpoint dir;
    otherwise random init of the named preset (demo mode)."""
    if checkpoint:
        from agentfield_tpu.models.hf_loader import load_hf_checkpoint

        cfg, params = load_hf_checkpoint(checkpoint)
        model = checkpoint
        if tokenizer is None:
            try:
                tokenizer = HFTokenizer(checkpoint)
            except Exception:
                tokenizer = ByteTokenizer(cfg.vocab_size)
    else:
        cfg = get_config(model)
    if params is None:
        params = init_params(cfg, jax.random.PRNGKey(seed))
    if lora is not None:
        from agentfield_tpu.training.lora import load_adapter, merge_lora

        lcfg, adapter = load_adapter(lora)
        for t in lcfg.targets:  # EVERY target, both dims: a clear error
            # here beats an opaque XLA shape mismatch inside merge_lora
            base_shape = params["layers"][t].shape
            a_shape = adapter["layers"][f"{t}_a"].shape
            b_shape = adapter["layers"][f"{t}_b"].shape
            if (base_shape[0], base_shape[1]) != (a_shape[0], a_shape[1])                     or base_shape[2] != b_shape[2]:
                raise ValueError(
                    f"LoRA adapter {lora!r} was trained for a different "
                    f"model shape: target {t} is {base_shape}, adapter "
                    f"a={a_shape} b={b_shape}"
                )
        params = merge_lora(params, adapter, lcfg)  # BEFORE quantization
    if quant is not None:
        if quant != "int8":
            raise ValueError(f"unknown quant mode {quant!r} (have: 'int8')")
        from agentfield_tpu.models.quant import quantize_params

        params = quantize_params(params)
    if tokenizer is None:
        tokenizer = ByteTokenizer(cfg.vocab_size)
    if ecfg is None:
        # Default node config serves constrained decoding out of the box —
        # 256 int16 bank rows (~66 MB at a 128k vocab) cover several live
        # schemas; idle ones evict LRU under pressure.
        ecfg = EngineConfig(grammar_slots=256)
    import os as _os

    _sk = _os.environ.get("AGENTFIELD_PREFIX_SKETCH_BYTES")
    if _sk is not None:
        # Operator override of the heartbeat sketch byte cap (docs/
        # OPERATIONS.md "Cluster prefix cache"); 0 stops publication.
        import dataclasses as _dc2

        try:
            ecfg = _dc2.replace(ecfg, prefix_sketch_bytes=int(_sk))
        except ValueError:
            pass  # malformed env override keeps the configured default
    # Agent-aware serving knobs (docs/OPERATIONS.md "Agent-aware serving
    # (runbook)"): same contract as the sketch override — a malformed value
    # keeps the configured default, never fails serve startup.
    import dataclasses as _dc3

    _spec_env = (
        ("AGENTFIELD_SPEC_PREFILL", "spec_prefill", lambda v: v.strip().lower() not in ("0", "false", "no", "off")),
        ("AGENTFIELD_SPEC_PIN_TTL_S", "spec_pin_ttl", float),
        ("AGENTFIELD_SPEC_PIN_BUDGET", "spec_pin_budget", int),
        ("AGENTFIELD_SPEC_MAX_CANDIDATES", "spec_max_candidates", int),
    )
    for _env_name, _field, _parse in _spec_env:
        _v = _os.environ.get(_env_name)
        if _v is not None:
            try:
                ecfg = _dc3.replace(ecfg, **{_field: _parse(_v)})
            except ValueError:
                pass
    draft = None
    if spec_k is not None:
        import dataclasses as _dc

        ecfg = _dc.replace(ecfg, spec_k=spec_k)
    if ecfg.spec_k > 0:
        if spec_draft is None:
            raise ValueError("spec_k > 0 needs spec_draft=<model preset>")
        draft = load_draft_model(spec_draft, cfg.vocab_size, seed=seed + 4)
    mesh = None
    if tp > 1:
        from agentfield_tpu.parallel.mesh import AXIS_MODEL, make_mesh

        mesh = make_mesh({AXIS_MODEL: tp})
    backend = ModelBackend(
        params, cfg, ecfg, tokenizer=tokenizer, seed=seed, model_name=model,
        mesh=mesh, vision=vision, grammar_whitespace=grammar_whitespace,
        audio=audio, tts=tts, imagegen=imagegen, draft=draft,
    )

    # Advertise served modalities so callers can route capability-needing
    # requests to a node that actually has the tower/head (SDK
    # _model_candidates prefers advertising nodes; reference analogue: the
    # provider-model fallback chain picks models by capability,
    # agent_ai.py:345-384).
    modalities = ["text"]
    if backend.vision_cfg is not None:
        modalities.append("image-in")
    if backend.audio_cfg is not None:
        modalities.append("audio-in")
    if backend.tts_cfg is not None:
        modalities.append("audio-out")
    if backend.imagegen_cfg is not None:
        modalities.append("image-out")
    # Role advertisement (disaggregated pools): registration metadata is the
    # ONE channel — the registry's snapshot cache surfaces it to _pick_node
    # without a schema change, and the sweep loop turns it into the
    # per-role nodes_by_role gauge.
    role = role or _os.environ.get("AGENTFIELD_NODE_ROLE") or "mixed"
    if role not in ("prefill", "decode", "mixed"):
        raise ValueError(
            f"unknown node role {role!r}: 'prefill' | 'decode' | 'mixed' "
            "(AGENTFIELD_NODE_ROLE / build_model_node(role=...))"
        )
    kwargs: dict[str, Any] = {
        "kind": "model",
        "metadata": {"model": model, "modalities": modalities, "role": role},
    }
    if control_plane:
        kwargs["control_plane"] = control_plane
    agent = Agent(node_id, **kwargs)
    # The bound method's own signature drives schema synthesis — no
    # hand-maintained forwarding wrapper to drift out of sync.
    agent.reasoner(id="generate", description=f"TPU-served {model} generation")(
        backend.generate
    )
    agent.reasoner(id="embed", description=f"TPU-served {model} embeddings")(
        backend.embed
    )
    # Engine counters ride the 2s heartbeats → cluster-visible via
    # /api/v1/nodes metadata and the dashboard.
    def _heartbeat_stats():
        stats = {
            **backend.engine.stats,
            **backend.engine.grammar_bank_stats(),
            **backend.engine.prefix_cache_stats(),
            **backend.engine.scheduler_stats(),  # itl_ms_p50/p99, tokens_per_tick
            # node-side data-plane counters ride the same heartbeat → /stats →
            # per-node Prometheus gauge pipeline as the engine counters
            **(agent.channel_server.stats if agent.channel_server is not None else {}),
            "active_slots": backend.engine.num_active,
            "pending_requests": len(backend.engine.pending),
            "free_pages": backend.engine.allocator.free_pages,
            "draining": int(backend._draining),
            # Always-on latency histograms (TTFT/ITL/queue-wait/tick, ms
            # buckets): popped by the registry like prefix_sketch and
            # re-exported as REAL per-node Prometheus histograms —
            # percentile gauges can't aggregate across a fleet, bucket
            # counts can (docs/OBSERVABILITY.md).
            "latency_hist": backend.engine.latency_histograms(),
        }
        # Cluster prefix tier (docs/PREFIX_CACHING.md "Cluster tier"): the
        # prefix-index sketch rides every heartbeat; the registry pops it
        # into the affinity side table (it is a routing signal, not a
        # numeric stat — export_engine_stats would skip it anyway).
        sketch = backend.engine.prefix_sketch()
        if sketch is not None:
            stats["prefix_sketch"] = sketch
        return stats

    agent.heartbeat_stats = _heartbeat_stats

    async def _prep_stream_kwargs(body: dict) -> dict:
        """Shared request prep for both token-stream transports (direct SSE
        and the gateway channel): chat template, grammar pre-warm, media
        pre-fusion — one recipe, so the two paths cannot drift."""
        gen_kwargs = {
            k: body[k]
            for k in (
                "prompt", "tokens", "stop_token_ids", "session_id",
                "max_new_tokens", "temperature", "top_k", "top_p",
                "response_schema", "context_overflow", "images", "audios",
                "deadline_s", "priority", "n_branches", "branch_policy",
                "trace", "handoff", "expect_followup", "followup_candidates",
            )
            if body.get(k) is not None
        }
        if body.get("messages") is not None:
            if gen_kwargs.get("prompt") is not None or gen_kwargs.get("tokens") is not None:
                raise ValueError("messages is exclusive with prompt/tokens")
            gen_kwargs["prompt"] = backend.apply_chat_template(body["messages"])
        if body.get("output") not in (None, "text"):
            raise ValueError(
                "the token stream is text-only; use the unary generate "
                "path for output='audio'/'speech'/'image'"
            )
        if gen_kwargs.get("response_schema") is not None:
            gen_kwargs["grammar_obj"] = await backend.ensure_grammar(
                gen_kwargs["response_schema"]
            )
        if (gen_kwargs.get("images") or gen_kwargs.get("audios")) \
                and gen_kwargs.get("prompt") is not None \
                and gen_kwargs.get("tokens") is None:
            gen_kwargs["prefused"] = await backend.ensure_media(
                gen_kwargs["prompt"], gen_kwargs.get("images"),
                gen_kwargs.get("audios"),
            )
        if body.get("kv_peer") is not None and gen_kwargs.get("tokens") is not None \
                and not (gen_kwargs.get("images") or gen_kwargs.get("audios")):
            # Cluster prefix tier: pull the advertised prefix pages from the
            # hinted peer BEFORE submit, so admission's lookup restores them
            # (kv_peer is a transport hint, not a sampling kwarg — it never
            # reaches submit_stream).
            await backend.maybe_prefetch_kv(gen_kwargs["tokens"], body["kv_peer"])
        return gen_kwargs

    def _event_frame(ev) -> dict:
        frame = {
            "token": ev.token,
            "index": ev.index,
            "finished": ev.finished,
            "finish_reason": ev.finish_reason,
            "logprob": ev.logprob,
        }
        if backend.tokenizer is not None and ev.token >= 0:
            frame["text"] = backend.tokenizer.decode([ev.token])
        return frame

    async def stream_handler(req):
        """SSE token stream — the data-plane path: callers hit the model node
        directly so tokens never proxy through the control plane (reference
        streams pass through litellm, agent_ai.py:414-416; here the transport
        is ours)."""
        import json as _json

        from aiohttp import web as _web

        try:
            body = await req.json()
            if not isinstance(body, dict):
                raise ValueError("JSON object body required")
            gen_kwargs = await _prep_stream_kwargs(body)
            rid, q, _truncated = backend.submit_stream(**gen_kwargs)
        except (QueueFullError,) as e:
            return _web.json_response({"error": str(e)}, status=503)
        except Exception as e:
            return _web.json_response({"error": repr(e)}, status=400)
        resp = _web.StreamResponse(
            headers={"Content-Type": "text/event-stream", "Cache-Control": "no-cache"}
        )
        await resp.prepare(req)
        try:
            while True:
                try:
                    # wait_for, not aio_timeout: the backport cancels the
                    # ENCLOSING task at the deadline, so a client-disconnect
                    # cancel in that window was relabeled TimeoutError and
                    # the loop absorbed it (afcheck task-lifecycle; the
                    # PR 11 stop()-hang class)
                    ev = await asyncio.wait_for(q.get(), 10)
                except asyncio.TimeoutError:
                    # Idle decode gap (deep queue / long prefill): comment
                    # frames keep the stream alive through proxies.
                    await resp.write(b": ping\n\n")
                    continue
                frame = _event_frame(ev)
                if ev.finished:
                    meta = backend.pop_group_meta(rid)
                    if meta is not None:
                        frame["branches"] = meta  # branch-group summary
                        # rides the terminal frame
                await resp.write(f"data: {_json.dumps(frame)}\n\n".encode())
                if ev.finished:
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            # Consumer gone mid-stream: CANCEL the request — decoding for a
            # dead reader wastes TPU steps and pins pages (same policy as
            # generate()'s CancelledError path).
            backend.cancel(rid)
        except Exception as e:
            # The terminal-before-close contract: a transport-capable client
            # must be able to tell "server failed" from "link dropped".
            try:
                await resp.write(
                    f"data: {_json.dumps({'token': -1, 'index': -1, 'finished': True, 'finish_reason': f'error: {e!r}'})}\n\n".encode()
                )
            except (ConnectionResetError, RuntimeError):
                pass  # client is gone too; the engine-side cancel below still runs
            backend.cancel(rid)
        finally:
            backend.release_stream(rid)  # disconnected consumers must not
            # accumulate in _streams
        return resp

    agent.add_route("POST", "/generate/stream", stream_handler)

    async def channel_generate(payload, headers, emit):
        """Gateway-channel streaming handler for `generate`: TokenEvents →
        channel token frames, final result identical in shape to the unary
        generate() (so an execution's recorded result is transport-
        independent). Cancellation (gateway cancel frame / deadline) lands
        here as CancelledError → engine cancel path frees the slot."""
        if not isinstance(payload, dict):
            raise ValueError("generate input must be a JSON object")
        if payload.get("output") not in (None, "text"):
            # Non-text outputs don't stream: serve them unary over the
            # channel (terminal frame only), result identical to POST.
            return await backend.generate(
                **{k: v for k, v in payload.items() if v is not None}
            )
        trace_ctx = tracing.valid_context(payload.get("trace"))
        t0_w, t0_m = time.time(), time.perf_counter()
        gen_kwargs = await _prep_stream_kwargs(payload)
        rid, q, truncated = backend.submit_stream(**gen_kwargs)
        records: list[tuple[int, float | None]] = []
        finish_reason = None
        branches_meta = None
        try:
            while True:
                ev = await q.get()
                await emit(_event_frame(ev))
                if ev.token < 0:
                    pass  # terminal marker without content (deadline/error)
                elif not (ev.finished and ev.finish_reason == "stop"):
                    records.append((ev.token, ev.logprob))
                if ev.finished:
                    finish_reason = ev.finish_reason
                    # Branch groups: the winner's summary lands with its
                    # replayed terminal (popped BEFORE release_stream's
                    # abandoned-meta backstop runs in the finally below).
                    branches_meta = backend.pop_group_meta(rid)
                    break
        except asyncio.CancelledError:
            backend.cancel(rid)
            raise
        finally:
            backend.release_stream(rid)
            if trace_ctx is not None:
                # The node-side envelope span, streamed path (its unary twin
                # lives in generate()): recorded in the finally so a cancel
                # or engine failure still leaves it for the channel
                # server's terminal-time collection.
                _tr = tracing.tracer()
                _tr.record_span(
                    "node.generate", trace_ctx["trace_id"], t0_w,
                    (time.perf_counter() - t0_m) * 1e3,
                    {"rid": rid, "finish": finish_reason, "stream": 1},
                )
        if finish_reason and finish_reason.startswith("error:"):
            raise RuntimeError(f"engine stream failed ({finish_reason})")
        result = {
            "tokens": [t for t, _ in records],
            "logprobs": [lp for _, lp in records],
            "finish_reason": finish_reason,
            "model": backend.model_name,
        }
        if branches_meta is not None:
            result["branches"] = branches_meta
        if backend.tokenizer is not None:
            result["text"] = backend.tokenizer.decode(result["tokens"])
        if truncated:
            result["truncated_prompt_tokens"] = truncated
        return result

    if agent.channel_server is not None:
        agent.channel_stream("generate", channel_generate)
        # Cluster prefix tier: serve peers' kv_fetch frames from this
        # engine's prefix index, and ride the same channel (gateway-relayed)
        # for this node's own pulls.
        agent.channel_server.set_kv_export(backend.kv_export_pages)
        backend._kv_fetch_fn = agent.channel_server.fetch_kv
        # Tracing: the channel server collects this trace's spans at
        # TERMINAL time — success, failure, and cancel terminals alike, so
        # a node that failed an execution still ships its evidence
        # (docs/OBSERVABILITY.md).
        agent.channel_server.set_trace_collect(backend.collect_trace_spans)

    async def _branch_verifier(target: str, payload: dict) -> Any:
        """Branch-group verifier hook: dispatch the candidate texts to the
        named reasoner THROUGH the gateway (the control plane as a
        reranker — docs/PREFIX_CACHING.md "Fork / COW branches"). A
        non-completed execution raises; the group falls back to logprob."""
        doc = await agent.client.execute(target, payload)
        if doc.get("status") != "completed":
            raise RuntimeError(
                f"verifier {target!r} {doc.get('status')}: {doc.get('error')}"
            )
        return doc.get("result")

    backend._verifier_call = _branch_verifier

    async def stats_handler(_req):
        from aiohttp import web as _web

        eng = backend.engine
        return _web.json_response(
            {
                "model": backend.model_name,
                **eng.stats,
                **eng.prefix_cache_stats(),
                **eng.scheduler_stats(),  # itl_ms_p50/p99, tokens_per_tick
                "active_slots": eng.num_active,
                "pending": len(eng.pending),
                "free_pages": eng.allocator.free_pages,
            }
        )

    agent.add_route("GET", "/stats", stats_handler)

    async def flight_handler(req):
        """Node debug endpoint (docs/OBSERVABILITY.md "Flight recorder"):
        the last N per-tick scheduler records — tick mode, batch
        composition, token load, page headroom, overload counters. Always
        on; ``?last=64`` bounds the dump."""
        from aiohttp import web as _web

        try:
            last = int(req.query.get("last", "0")) or None
        except ValueError:
            last = None
        eng = backend.engine
        return _web.json_response(
            {
                "node_id": node_id,
                "max_ticks": eng.flight.max_ticks,
                "ticks_recorded": eng.flight.ticks_recorded,
                "trace_buffer_spans": eng._tracer.span_count(),
                "trace_spans_dropped": eng._tracer.dropped_spans,
                "ticks": eng.flight.snapshot(last=last),
            }
        )

    agent.add_route("GET", "/debug/flight", flight_handler)

    profile_state = {"active": False, "dir": None}

    async def profile_handler(req):
        """jax.profiler trace capture (the TPU-native answer to SURVEY §5's
        tracing row: the reference leans on pprof/gops; here device traces
        open in TensorBoard/XProf). POST /profile/start {"dir": ...} then
        POST /profile/stop."""
        from aiohttp import web as _web

        action = req.match_info["action"]
        if action == "start":
            # Read the body BEFORE the check-and-set: an await between check
            # and set would let two concurrent starts both pass (TOCTOU).
            try:
                body = await req.json() if req.can_read_body else {}
            except Exception:
                body = {}
            if not isinstance(body, dict):
                body = {}
            if profile_state["active"]:
                return _web.json_response({"error": "trace already active"}, status=409)
            profile_state["active"] = True  # claim first; no awaits until done
            trace_dir = body.get("dir") or "/tmp/agentfield_tpu_trace"
            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as e:
                profile_state["active"] = False
                return _web.json_response({"error": f"start_trace failed: {e!r}"}, status=500)
            profile_state["dir"] = trace_dir
            return _web.json_response({"tracing": True, "dir": trace_dir})
        if action == "stop":
            if not profile_state["active"]:
                return _web.json_response({"error": "no active trace"}, status=409)
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                return _web.json_response({"error": f"stop_trace failed: {e!r}"}, status=500)
            finally:
                profile_state["active"] = False  # never wedge the endpoint
            return _web.json_response({"tracing": False, "dir": profile_state["dir"]})
        return _web.json_response({"error": "action must be start|stop"}, status=404)

    agent.add_route("POST", "/profile/{action}", profile_handler)
    return agent, backend


async def drain_and_stop(agent: Agent, backend: ModelBackend, grace_s: float = 30.0) -> dict:
    """The full rolling-restart sequence (docs/OPERATIONS.md runbook):
    stop admitting → finish/deadline-out in-flight work → deregister from
    the control plane (placement stops immediately; the registry fires its
    node-down hook, which finds nothing in flight because the drain already
    answered every caller) → unbind. Returns the drain summary."""
    summary = await backend.drain(grace_s)
    try:
        await agent.client.deregister_node(agent.node_id)
    # afcheck: ignore[except-swallow] plane unreachable during shutdown: the lease sweep evicts us either way
    except Exception:
        pass
    await agent.stop()
    await backend.stop()
    return summary


def install_sigterm_drain(
    agent: Agent, backend: ModelBackend, grace_s: float = 30.0
) -> asyncio.Event:
    """Wire SIGTERM (and SIGINT) to the graceful drain. Returns an Event set
    when the drain+shutdown completes — serve loops await it instead of
    sleeping forever. Call from the running event loop."""
    import signal

    done = asyncio.Event()
    loop = asyncio.get_running_loop()
    started = False
    holder: set = set()  # strong ref: loop tasks are weakly held and a
    # GC'd drain task would strand the process mid-shutdown

    def _on_signal():
        nonlocal started
        if started:
            return  # second signal during drain: ignore (drain is bounded)
        started = True

        async def run():
            try:
                summary = await drain_and_stop(agent, backend, grace_s)
                print(f"[agentfield] {agent.node_id} drained: {summary}", flush=True)
            finally:
                done.set()

        t = loop.create_task(run())
        holder.add(t)
        t.add_done_callback(holder.discard)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _on_signal)
        except (NotImplementedError, RuntimeError):
            pass  # platform without loop signal support (tests call drain directly)
    return done


# Optional scalar fields of GenerateRequest, shared by the server-side
# decode and the client-side encode so the two cannot drift.
_GRPC_SCALAR_FIELDS = ("prompt", "max_new_tokens", "temperature", "top_k",
                       "top_p", "session_id", "context_overflow")


def _grpc_request_to_kwargs(request) -> dict[str, Any]:
    """GenerateRequest proto → backend.generate kwargs. `optional` fields
    pass through only when present, so server-side defaults (top_p=1 etc.)
    stay authoritative."""
    import json as _json

    kwargs: dict[str, Any] = {}
    for f in _GRPC_SCALAR_FIELDS:
        if request.HasField(f):
            kwargs[f] = getattr(request, f)
    if request.tokens:
        kwargs["tokens"] = list(request.tokens)
    if request.stop_token_ids:
        kwargs["stop_token_ids"] = list(request.stop_token_ids)
    if request.HasField("response_schema_json"):
        kwargs["response_schema"] = _json.loads(request.response_schema_json)
    if request.images:
        # raw encoded bytes straight through — _decode_image takes them
        # as-is (no base64 round trip on the data-plane hot path)
        kwargs["images"] = list(request.images)
    return kwargs


def _result_to_grpc_response(result: dict[str, Any]):
    from agentfield_tpu.control_plane.proto import modelnode_pb2

    return modelnode_pb2.GenerateResponse(
        tokens=result.get("tokens", []),
        text=result.get("text", ""),
        finish_reason=result.get("finish_reason") or "",
        model=result.get("model", ""),
        logprobs=[lp for lp in (result.get("logprobs") or []) if lp is not None],
        truncated_prompt_tokens=int(result.get("truncated_prompt_tokens", 0)),
    )


class ModelGrpcService:
    """gRPC surface for the model node's hot path (BASELINE.json north star:
    ai() routes 'via gRPC to a JAX/XLA model node'). Real protobuf messages
    (vendored proto/modelnode.proto, protoc-generated like the admin
    service); the unary Generate blocks until completion, mirroring
    backend.generate."""

    SERVICE = "agentfield.model.v1.ModelNode"

    def __init__(self, backend: ModelBackend, loop: asyncio.AbstractEventLoop):
        self.backend = backend
        self.loop = loop

    def service(self, handler_call_details):
        import grpc

        from agentfield_tpu.control_plane.proto import modelnode_pb2

        if handler_call_details.method != f"/{self.SERVICE}/Generate":
            return None

        def generate(request, context):
            try:
                kwargs = _grpc_request_to_kwargs(request)
            except ValueError as e:  # malformed response_schema_json etc.
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            fut = asyncio.run_coroutine_threadsafe(
                self.backend.generate(**kwargs), self.loop
            )
            try:
                # Honor the caller's deadline (bounded default) and CANCEL the
                # coroutine if it expires — a hung generation must release
                # both this worker thread and its engine slot.
                remaining = context.time_remaining()
                timeout = min(remaining, 600.0) if remaining is not None else 600.0
                return _result_to_grpc_response(fut.result(timeout=timeout))
            except TimeoutError:
                fut.cancel()
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "generation timed out")
            except QueueFullError as e:
                # Admission backpressure is retryable — mirror the SSE path's
                # 503 (reference queue-full semantics, execute.go:1373-1410).
                fut.cancel()
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except RequestTooLongError as e:
                fut.cancel()
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except Exception as e:
                fut.cancel()
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        return grpc.unary_unary_rpc_method_handler(
            generate,
            request_deserializer=modelnode_pb2.GenerateRequest.FromString,
            response_serializer=modelnode_pb2.GenerateResponse.SerializeToString,
        )


def start_model_grpc(backend: ModelBackend, port: int) -> "object":
    """Serve Generate on `port`. Call from the event-loop thread (captures the
    running loop for cross-thread coroutine dispatch)."""
    from concurrent import futures as _futures

    import grpc

    loop = asyncio.get_running_loop()
    server = grpc.server(_futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((ModelGrpcService(backend, loop),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    if bound == 0:
        raise OSError(f"model gRPC could not bind 127.0.0.1:{port}")
    server.start()
    return server


def model_grpc_generate(port: int, request: dict, timeout: float = 600.0) -> dict:
    """Client helper for the gRPC Generate surface. Accepts the same dict
    shape as the HTTP body (response_schema as a dict, images as
    {"b64": ...} entries) and converts to/from the proto messages."""
    import base64 as _b64
    import json as _json

    import grpc

    from agentfield_tpu.control_plane.proto import modelnode_pb2

    msg = modelnode_pb2.GenerateRequest()
    for f in _GRPC_SCALAR_FIELDS:
        if request.get(f) is not None:
            setattr(msg, f, request[f])
    if request.get("tokens"):
        msg.tokens.extend(request["tokens"])
    if request.get("stop_token_ids"):
        msg.stop_token_ids.extend(request["stop_token_ids"])
    if request.get("response_schema") is not None:
        msg.response_schema_json = _json.dumps(request["response_schema"])
    for im in request.get("images") or []:
        if not (isinstance(im, dict) and "b64" in im):
            raise ValueError("gRPC images must be {'b64': <base64 bytes>} entries")
        msg.images.append(_b64.b64decode(im["b64"]))

    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        fn = channel.unary_unary(
            f"/{ModelGrpcService.SERVICE}/Generate",
            request_serializer=modelnode_pb2.GenerateRequest.SerializeToString,
            response_deserializer=modelnode_pb2.GenerateResponse.FromString,
        )
        resp = fn(msg, timeout=timeout)
    out: dict[str, Any] = {
        "tokens": list(resp.tokens),
        "text": resp.text,
        "finish_reason": resp.finish_reason or None,
        "model": resp.model,
    }
    if resp.logprobs:
        out["logprobs"] = list(resp.logprobs)
    if resp.truncated_prompt_tokens:
        out["truncated_prompt_tokens"] = resp.truncated_prompt_tokens
    return out
