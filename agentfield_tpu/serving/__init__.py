from agentfield_tpu.serving.kv_cache import PageAllocator, PagedKVCache  # noqa: F401
from agentfield_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    InferenceEngine,
    Request,
    TokenEvent,
)
from agentfield_tpu.serving.sampler import SamplingParams  # noqa: F401
