from agentfield_tpu.serving.kv_cache import (  # noqa: F401
    PageAllocator,
    PagedKVCache,
    PrefixPagePool,
)
from agentfield_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    GrammarCapacityError,
    InferenceEngine,
    QueueFullError,
    Request,
    RequestTooLongError,
    TokenEvent,
)
from agentfield_tpu.serving.grammar import (  # noqa: F401
    Grammar,
    SchemaError,
    compile_json_schema,
)
from agentfield_tpu.serving.sampler import SamplingParams  # noqa: F401
