"""Constrained decoding: JSON schema → token-level DFA for sampler masking.

The reference enforces structured output by prompt injection and salvages the
result with regex (sdk/python/agentfield/agent_ai.py:221-245, 424-447). The
TPU-native replacement makes schema-invalid tokens *unsampleable*: a JSON
schema compiles to a character-level DFA, which closes over the tokenizer
vocabulary into a token-level transition table ``trans[state, token] →
next_state | -1``. The serving engine keeps the table device-resident and, at
every decode step, masks logits with ``trans[state] >= 0`` before sampling and
advances ``state = trans[state, sampled]`` on-device — so constrained rows ride
the same jitted decode step as free rows, with no host round-trip and no
re-parse fallback.

Pipeline:
  schema --(build_json_nfa)--> byte-level NFA fragments (concat/alt/star)
         --(subset construction)--> DFA over byte classes
         --(close_over_vocab, numpy-vectorized)--> Grammar(trans, accept)

Generation defaults to canonical compact JSON: object properties in schema
order, no whitespace — a deliberate restriction that keeps the automaton small
and the output deterministic to validate. Two v2 relaxations are available:

- ``required``: when a schema object carries a ``required`` list, only those
  properties must appear; the rest are optional (still in declaration order,
  comma placement handled by the automaton). Without ``required`` every
  declared property is emitted (v1-compatible canonical form).
- ``whitespace=True`` (``compile_json_schema``): accepts up to ``max_ws``
  whitespace bytes (space/tab/CR/LF) after ``{`` ``[`` ``,`` ``:`` and before
  ``}`` ``]`` — enough for pretty-printed output. Bounded repetition (not a
  Kleene star) so masked generation can never stall in an infinite-whitespace
  loop: after ``max_ws`` blanks the only legal continuation is real JSON.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

# ---------------------------------------------------------------------------
# NFA with byte-range edges
# ---------------------------------------------------------------------------

EPS = -1  # epsilon edge marker


class _NFA:
    """Thompson-style NFA builder. States are ints; edges are (lo, hi) byte
    ranges (inclusive) or epsilon. Fragments expose (start, accept) and are
    combined functionally."""

    # Hard bound on NFA construction: schemas arrive over the wire
    # (model_node compiles per-request), and $ref fan-out can blow a
    # few-KB schema up exponentially — fail with SchemaError, not OOM.
    MAX_STATES = 200_000

    def __init__(self):
        self.edges: list[list[tuple[int, int, int]]] = []  # state -> [(lo, hi, dst)]
        self.eps: list[list[int]] = []  # state -> [dst]

    def state(self) -> int:
        if len(self.edges) >= self.MAX_STATES:
            raise SchemaError(
                f"schema expands past {self.MAX_STATES} NFA states "
                "(deep $ref fan-out?) — simplify or bound the schema"
            )
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1

    def add(self, src: int, lo: int, hi: int, dst: int) -> None:
        self.edges[src].append((lo, hi, dst))

    def add_eps(self, src: int, dst: int) -> None:
        self.eps[src].append(dst)

    # -- fragments ---------------------------------------------------------

    def lit(self, text: str | bytes) -> tuple[int, int]:
        data = text.encode("utf-8") if isinstance(text, str) else text
        start = self.state()
        cur = start
        for b in data:
            nxt = self.state()
            self.add(cur, b, b, nxt)
            cur = nxt
        return start, cur

    def char_class(self, ranges: list[tuple[int, int]]) -> tuple[int, int]:
        start, end = self.state(), self.state()
        for lo, hi in ranges:
            self.add(start, lo, hi, end)
        return start, end

    def concat(self, *frags: tuple[int, int]) -> tuple[int, int]:
        frags = [f for f in frags if f is not None]
        if not frags:
            s = self.state()
            return s, s
        for (_, a_end), (b_start, _) in zip(frags, frags[1:]):
            self.add_eps(a_end, b_start)
        return frags[0][0], frags[-1][1]

    def alt(self, *frags: tuple[int, int]) -> tuple[int, int]:
        start, end = self.state(), self.state()
        for f_start, f_end in frags:
            self.add_eps(start, f_start)
            self.add_eps(f_end, end)
        return start, end

    def star(self, frag: tuple[int, int]) -> tuple[int, int]:
        start, end = self.state(), self.state()
        self.add_eps(start, frag[0])
        self.add_eps(frag[1], frag[0])
        self.add_eps(frag[1], end)
        self.add_eps(start, end)
        return start, end

    def opt(self, frag: tuple[int, int]) -> tuple[int, int]:
        start, end = self.state(), self.state()
        self.add_eps(start, frag[0])
        self.add_eps(frag[1], end)
        self.add_eps(start, end)
        return start, end

    def plus(self, frag: tuple[int, int]) -> tuple[int, int]:
        return self.concat(frag, self.star(frag))


# ---------------------------------------------------------------------------
# JSON-schema → NFA
# ---------------------------------------------------------------------------

_ASCII_STRING_RANGES = [
    (0x20, 0x21),  # printable minus '"' (0x22) and '\' (0x5C)
    (0x23, 0x5B),
    (0x5D, 0x7E),
]
_ESCAPABLE = b'"\\/bfnrt'
_DIGIT = [(0x30, 0x39)]
_DIGIT19 = [(0x31, 0x39)]


class SchemaError(ValueError):
    pass


def _utf8_char(n: _NFA) -> tuple[int, int]:
    """One well-formed multi-byte UTF-8 character (RFC 3629 table — excludes
    overlongs and surrogates). Byte-level BPE tokens can be partial UTF-8
    fragments, so the DFA must track continuation structure or masked
    generation could stitch invalid byte sequences across token boundaries."""
    cont = lambda: n.char_class([(0x80, 0xBF)])
    two = n.concat(n.char_class([(0xC2, 0xDF)]), cont())
    three = n.alt(
        n.concat(n.char_class([(0xE0, 0xE0)]), n.char_class([(0xA0, 0xBF)]), cont()),
        n.concat(n.char_class([(0xE1, 0xEC), (0xEE, 0xEF)]), cont(), cont()),
        n.concat(n.char_class([(0xED, 0xED)]), n.char_class([(0x80, 0x9F)]), cont()),
    )
    four = n.alt(
        n.concat(n.char_class([(0xF0, 0xF0)]), n.char_class([(0x90, 0xBF)]), cont(), cont()),
        n.concat(n.char_class([(0xF1, 0xF3)]), cont(), cont(), cont()),
        n.concat(n.char_class([(0xF4, 0xF4)]), n.char_class([(0x80, 0x8F)]), cont(), cont()),
    )
    return n.alt(two, three, four)


def _string_body(n: _NFA) -> tuple[int, int]:
    """Characters inside a JSON string: plain ASCII, well-formed UTF-8
    multibyte chars, or \\-escapes (incl. \\uXXXX)."""
    plain = n.alt(n.char_class(_ASCII_STRING_RANGES), _utf8_char(n))
    esc_simple = n.concat(n.lit("\\"), n.char_class([(c, c) for c in _ESCAPABLE]))
    hexd = [(0x30, 0x39), (0x41, 0x46), (0x61, 0x66)]
    esc_u = n.concat(
        n.lit("\\u"),
        n.char_class(hexd), n.char_class(hexd), n.char_class(hexd), n.char_class(hexd),
    )
    return n.star(n.alt(plain, esc_simple, esc_u))


def _json_string(n: _NFA, max_length: int | None = None) -> tuple[int, int]:
    if max_length is not None:
        # NFA fragments are graph nodes, not reusable combinators — each
        # character position needs a freshly built fragment (sharing one would
        # let later positions re-enter earlier states, i.e. an unbounded loop).
        hexd = [(0x30, 0x39), (0x41, 0x46), (0x61, 0x66)]

        def one_char():
            plain = n.alt(n.char_class(_ASCII_STRING_RANGES), _utf8_char(n))
            esc = n.concat(n.lit("\\"), n.char_class([(c, c) for c in _ESCAPABLE]))
            esc_u = n.concat(
                n.lit("\\u"),
                n.char_class(hexd), n.char_class(hexd), n.char_class(hexd), n.char_class(hexd),
            )
            return n.alt(plain, esc, esc_u)

        body = None
        for _ in range(max_length):
            piece = n.opt(one_char())
            body = piece if body is None else n.concat(body, piece)
        return n.concat(n.lit('"'), body, n.lit('"')) if body else n.lit('""')
    return n.concat(n.lit('"'), _string_body(n), n.lit('"'))


def _json_number(n: _NFA, integer: bool = False) -> tuple[int, int]:
    sign = n.opt(n.lit("-"))
    int_part = n.alt(n.lit("0"), n.concat(n.char_class(_DIGIT19), n.star(n.char_class(_DIGIT))))
    if integer:
        return n.concat(sign, int_part)
    frac = n.opt(n.concat(n.lit("."), n.plus(n.char_class(_DIGIT))))
    exp = n.opt(
        n.concat(
            n.char_class([(0x45, 0x45), (0x65, 0x65)]),  # e | E
            n.opt(n.char_class([(0x2B, 0x2B), (0x2D, 0x2D)])),  # + | -
            n.plus(n.char_class(_DIGIT)),
        )
    )
    return n.concat(sign, int_part, frac, exp)


_WS_RANGES = [(0x09, 0x0A), (0x0D, 0x0D), (0x20, 0x20)]  # \t \n \r space


def _make_ws(n: _NFA, max_ws: int):
    """Returns a factory for fresh optional-whitespace fragments (≤ max_ws
    blanks), or a None-returning factory when whitespace is disabled.
    Fragments are graph nodes, so every insertion point needs its own."""
    if max_ws <= 0:
        return lambda: None

    def ws() -> tuple[int, int]:
        frag = None
        for _ in range(max_ws):
            piece = n.opt(n.char_class(_WS_RANGES))
            frag = piece if frag is None else n.concat(frag, piece)
        return frag

    return ws


def build_schema_nfa(
    n: _NFA, schema: dict[str, Any], depth: int = 0, ws=None,
    root: dict[str, Any] | None = None, active_refs: frozenset = frozenset(),
) -> tuple[int, int]:
    """Recursively build the NFA fragment for one schema node. Canonical
    compact JSON (properties in declaration order); `required` marks the
    mandatory subset, `ws()` (when enabled) yields optional-whitespace
    fragments inserted at structural boundaries.

    pydantic-emitted constructs are supported: ``$ref``/``$defs`` (resolved
    against ``root``; RECURSIVE refs are rejected — a DFA is finite and
    recursive JSON is not a regular language), ``anyOf``/``oneOf``
    (alternation; oneOf's exclusivity is relaxed to acceptance — standard in
    token-masking decoders), and single-element ``allOf`` (pydantic v1's
    ref-wrapping)."""
    if ws is None:
        ws = lambda: None
    if root is None:
        root = schema
    # depth counts STRUCTURAL nesting (arrays/objects) only; $ref/anyOf/
    # allOf unwrapping layers carry a separate, larger budget so pydantic
    # model chains (each level = object + $ref, often + allOf) aren't
    # rejected at half the advertised structural depth.
    if depth > 16:
        raise SchemaError("schema nesting deeper than 16 (arrays/objects)")
    if len(active_refs) > 64:
        raise SchemaError("more than 64 chained $refs")

    def recur(sub: dict, bump: bool = True, extra_ref: str | None = None):
        refs = active_refs | {extra_ref} if extra_ref else active_refs
        return build_schema_nfa(n, sub, depth + (1 if bump else 0), ws, root, refs)

    if "$ref" in schema:
        ref = schema["$ref"]
        if ref in active_refs:
            raise SchemaError(
                f"recursive $ref {ref!r}: a token-mask DFA is finite and "
                "cannot accept recursive schemas"
            )
        if not ref.startswith("#/"):
            raise SchemaError(f"only intra-document $ref supported, got {ref!r}")
        node: Any = root
        for part in ref[2:].split("/"):
            part = part.replace("~1", "/").replace("~0", "~")
            if not isinstance(node, dict) or part not in node:
                raise SchemaError(f"$ref {ref!r} does not resolve")
            node = node[part]
        return recur(node, bump=False, extra_ref=ref)
    if "anyOf" in schema or "oneOf" in schema:
        branches = schema.get("anyOf") or schema.get("oneOf")
        if not isinstance(branches, list) or not branches:
            raise SchemaError("anyOf/oneOf must be a non-empty list")
        return n.alt(*[recur(b, bump=False) for b in branches])
    if "allOf" in schema:
        branches = schema["allOf"]
        if isinstance(branches, list) and len(branches) == 1:
            # pydantic v1 wraps refs as allOf=[{$ref}] (+ sibling metadata)
            merged = {**branches[0], **{k: v for k, v in schema.items() if k != "allOf"}}
            return recur(merged, bump=False)
        raise SchemaError("allOf with multiple subschemas is not supported")
    if "enum" in schema:
        return n.alt(*[n.lit(json.dumps(v, separators=(",", ":"))) for v in schema["enum"]])
    if "const" in schema:
        return n.lit(json.dumps(schema["const"], separators=(",", ":")))
    t = schema.get("type")
    if isinstance(t, list):
        return n.alt(*[recur({**schema, "type": one}, bump=False) for one in t])
    if t == "string":
        return _json_string(n, schema.get("maxLength"))
    if t == "integer":
        return _json_number(n, integer=True)
    if t == "number":
        return _json_number(n)
    if t == "boolean":
        return n.alt(n.lit("true"), n.lit("false"))
    if t == "null":
        return n.lit("null")
    if t == "array":
        items = schema.get("items", {"type": ["string", "number", "boolean", "null"]})
        min_items = schema.get("minItems", 0)
        max_items = schema.get("maxItems")

        def item():
            return recur(items)

        def comma_item():
            return n.concat(n.lit(","), ws(), item())

        if max_items is not None:
            if max_items < min_items:
                raise SchemaError("maxItems < minItems")
            # Optionality must NEST (item (',' item)?)? — flat opt(item)
            # opt(',item') would accept a leading comma like '[,1]'. Build the
            # optional tail inside-out from the last position.
            tail = None  # optional ',item' chain after position i
            for _ in range(max_items - max(min_items, 1)):
                piece = comma_item()
                tail = n.opt(piece if tail is None else n.concat(piece, tail))
            if min_items >= 1:
                frag = None
                for i in range(min_items):
                    piece = item() if i == 0 else comma_item()
                    frag = piece if frag is None else n.concat(frag, piece)
                body = frag if tail is None else n.concat(frag, tail)
            else:
                first = item()
                body = n.opt(first if tail is None else n.concat(first, tail))
            return n.concat(n.lit("["), ws(), body, ws(), n.lit("]"))
        nonempty = n.concat(item(), n.star(comma_item()))
        body = nonempty if min_items >= 1 else n.opt(nonempty)
        return n.concat(n.lit("["), ws(), body, ws(), n.lit("]"))
    if t == "object" or "properties" in schema:
        props = list(schema.get("properties", {}).items())
        req = schema.get("required")
        if req is not None:
            unknown = set(req) - {name for name, _ in props}
            if unknown:
                # Checked before the empty-props early-out: an unsatisfiable
                # schema must fail loudly, not compile to a {}-only grammar.
                raise SchemaError(f"required names undeclared properties: {sorted(unknown)}")
        if not props:
            return n.concat(n.lit("{"), ws(), n.lit("}"))
        if req is None:
            # v1-compatible canonical form: every declared property emitted.
            required = {name for name, _ in props}
        else:
            required = set(req)

        def prop(name: str, sub: dict, lead_comma: bool) -> tuple[int, int]:
            parts = [n.lit(","), ws()] if lead_comma else []
            parts += [
                n.lit(json.dumps(name)),
                n.lit(":"),
                ws(),
                recur(sub),
            ]
            return n.concat(*[p for p in parts if p is not None])

        # tails[i]: properties i.. given one already emitted (comma-led); an
        # optional property alternates between appearing and falling through
        # to the rest. Shared-subgraph NFA, built inside-out like arrays.
        tails: list[tuple[int, int] | None] = [None] * (len(props) + 1)
        # heads only consume tails[1..] — property 0 is never comma-led
        for i in range(len(props) - 1, 0, -1):
            name, sub = props[i]
            full = prop(name, sub, True)
            if tails[i + 1] is not None:
                full = n.concat(full, tails[i + 1])
            if name in required:
                tails[i] = full
            elif tails[i + 1] is None:
                tails[i] = n.opt(full)
            else:
                tails[i] = n.alt(full, tails[i + 1])
        # heads: alternation over which property is emitted FIRST (no comma);
        # only properties preceded exclusively by optionals can be first.
        heads = []
        for i, (name, sub) in enumerate(props):
            h = prop(name, sub, False)
            if tails[i + 1] is not None:
                h = n.concat(h, tails[i + 1])
            heads.append(h)
            if name in required:
                break
        body = heads[0] if len(heads) == 1 else n.alt(*heads)
        if not required:  # fully-optional object may be empty
            body = n.opt(body)
        return n.concat(n.lit("{"), ws(), body, ws(), n.lit("}"))
    raise SchemaError(f"unsupported schema node: {schema!r}")


# ---------------------------------------------------------------------------
# NFA → DFA (subset construction over byte alphabet, class-compressed)
# ---------------------------------------------------------------------------


def _eps_closure(n: _NFA, states: frozenset[int]) -> frozenset[int]:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for d in n.eps[s]:
            if d not in seen:
                seen.add(d)
                stack.append(d)
    return frozenset(seen)


def nfa_to_dfa(n: _NFA, start: int, accept: int) -> tuple[np.ndarray, np.ndarray]:
    """Returns (T [n_states, 256] int32 with -1 = reject, accept_mask
    [n_states] bool). State 0 is the DFA start."""
    # Partition the byte alphabet into classes that behave identically to keep
    # subset construction cheap: boundaries from every edge's lo/hi+1.
    bounds = {0, 256}
    for src in range(len(n.edges)):
        for lo, hi, _ in n.edges[src]:
            bounds.add(lo)
            bounds.add(hi + 1)
    cuts = sorted(bounds)
    classes = list(zip(cuts[:-1], cuts[1:]))  # [(lo, hi_excl)]

    start_set = _eps_closure(n, frozenset([start]))
    dfa_states: dict[frozenset[int], int] = {start_set: 0}
    work = [start_set]
    trans_rows: list[dict[int, int]] = [{}]  # per dfa state: class idx -> dst

    while work:
        cur = work.pop()
        cur_id = dfa_states[cur]
        for ci, (lo, hi_excl) in enumerate(classes):
            nxt = set()
            for s in cur:
                for elo, ehi, dst in n.edges[s]:
                    if elo <= lo and hi_excl - 1 <= ehi:
                        nxt.add(dst)
            if not nxt:
                continue
            closed = _eps_closure(n, frozenset(nxt))
            if closed not in dfa_states:
                dfa_states[closed] = len(dfa_states)
                trans_rows.append({})
                work.append(closed)
            trans_rows[cur_id][ci] = dfa_states[closed]

    n_states = len(dfa_states)
    T = np.full((n_states, 256), -1, np.int32)
    for sid, row in enumerate(trans_rows):
        for ci, dst in row.items():
            lo, hi_excl = classes[ci]
            T[sid, lo:hi_excl] = dst
    accept_mask = np.zeros((n_states,), bool)
    for sset, sid in dfa_states.items():
        if accept in sset:
            accept_mask[sid] = True
    return T, accept_mask


# ---------------------------------------------------------------------------
# DFA × vocabulary → token-level Grammar
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Grammar:
    """Token-level automaton over a specific vocabulary.

    trans[state, token] = next state, or -1 if the token (or any byte inside
    it) leaves the language. accept[state] marks positions where the value is
    complete — the engine allows EOS exactly there (and only there for rows
    with no other outgoing transition).
    """

    trans: np.ndarray  # [n_states, vocab] int32
    accept: np.ndarray  # [n_states] bool
    start: int = 0

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def close_over_vocab(
    T: np.ndarray, accept: np.ndarray, vocab: list[bytes]
) -> Grammar:
    """Walk every vocab token through the byte DFA from every state at once
    (vectorized over states; iterates max-token-length times)."""
    n_states = T.shape[0]
    V = len(vocab)
    # Trap state n_states: all bytes stay trapped.
    T_ext = np.concatenate([T, np.full((1, 256), n_states, T.dtype)], axis=0)
    T_ext = np.where(T_ext < 0, n_states, T_ext)

    max_len = max((len(t) for t in vocab), default=1)
    byte_mat = np.zeros((V, max_len), np.int32)
    len_arr = np.zeros((V,), np.int32)
    for i, tok in enumerate(vocab):
        len_arr[i] = len(tok)
        if tok:
            byte_mat[i, : len(tok)] = np.frombuffer(tok, np.uint8)

    # state[v, s] = DFA state after feeding token v's first p bytes from s
    state = np.broadcast_to(np.arange(n_states, dtype=np.int32), (V, n_states)).copy()
    done = np.zeros((V, n_states), np.int32)
    for p in range(max_len):
        active = (len_arr > p)[:, None]  # tokens still feeding bytes
        stepped = T_ext[state, byte_mat[:, p][:, None]]
        state = np.where(active, stepped, state)
        if p + 1 <= max_len:
            just_done = (len_arr == p + 1)[:, None]
            done = np.where(just_done, state, done)
    done = np.where((len_arr == 0)[:, None], state, done)

    trans = np.where(done >= n_states, -1, done).astype(np.int32).T  # [n_states, V]
    # Zero-length tokens (shouldn't exist) stay in place; forbid them to be
    # safe — they would stall generation.
    if (len_arr == 0).any():
        trans[:, len_arr == 0] = -1
    return Grammar(trans=trans, accept=accept.copy(), start=0)


def compile_json_schema(
    schema: dict[str, Any],
    vocab: list[bytes],
    *,
    whitespace: bool = False,
    max_ws: int = 8,
) -> Grammar:
    """schema + tokenizer vocabulary → token-level Grammar.

    whitespace=True additionally accepts ≤ max_ws blanks at structural
    boundaries (pretty-printed output); bounded so generation cannot stall
    sampling whitespace forever."""
    n = _NFA()
    frag = build_schema_nfa(n, schema, ws=_make_ws(n, max_ws if whitespace else 0))
    T, accept = nfa_to_dfa(n, frag[0], frag[1])
    return close_over_vocab(T, accept, vocab)


def match_bytes(T: np.ndarray, accept: np.ndarray, data: bytes) -> bool:
    """Test helper: does the byte DFA accept `data`?"""
    s = 0
    for b in data:
        s = T[s, b]
        if s < 0:
            return False
    return bool(accept[s])
