"""Paged KV cache: device-resident page pool + host-side allocator.

TPU-first replacement for the reference's approach to context (the reference
merely *trims prompts* to fit an external provider's window —
sdk/python/agentfield/agent_ai.py:262-325). Here long sessions keep their KV
resident in HBM pages so agent→agent call chains never re-prefill
(SURVEY §5 "long-context" row, §7 step 7).

Layout: ``[num_layers, num_pages, num_kv_heads, page_size, head_dim]`` —
layers stacked on axis 0 so the decode step scans over them; the trailing
``(page_size, head_dim)`` block is a whole VMEM tile per (page, kv-head), which
is exactly the unit the Pallas paged-decode kernel DMAs (Mosaic requires the
last two block dims be full array dims or (8,128)-aligned — the former
``[.., ps, Kh, hd]`` layout forced (1, hd) blocks and failed TPU lowering).
Page 0 is reserved as a garbage sink: inactive decode slots write
there, which keeps the decode step shape-static with no host branching.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models.llama import resolve_dtype


@dataclasses.dataclass
class PagedKVCache:
    k_pages: jnp.ndarray  # [L, P, Kh, ps, hd]
    v_pages: jnp.ndarray  # [L, P, Kh, ps, hd]
    page_size: int

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @staticmethod
    def create(
        cfg: LlamaConfig,
        num_pages: int,
        page_size: int,
        dtype: str | None = None,
        mesh=None,
    ) -> "PagedKVCache":
        """With a mesh, pages shard over the KV-head axis on `model` (matching
        the TP sharding of wk/wv, so K/V writes during decode are local — no
        resharding on the hot path)."""
        dt = resolve_dtype(dtype or cfg.dtype)
        shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim)
        k = jnp.zeros(shape, dt)
        v = jnp.zeros(shape, dt)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from agentfield_tpu.parallel.mesh import AXIS_MODEL

            if mesh.shape.get(AXIS_MODEL, 1) > 1:
                s = NamedSharding(mesh, P(None, None, AXIS_MODEL, None, None))
                k, v = jax.device_put(k, s), jax.device_put(v, s)
        return PagedKVCache(k_pages=k, v_pages=v, page_size=page_size)

    def hbm_bytes(self) -> int:
        return 2 * self.k_pages.size * self.k_pages.dtype.itemsize


class PageAllocator:
    """Host-side free-list allocator over the device page pool.

    Page 0 is never handed out (garbage sink for inactive slots). This is the
    TPU analogue of the reference's queue-capacity backpressure
    (reference: internal/handlers/execute.go:333-346 returns HTTP 503 when the
    job queue is full): when no pages are free, admission fails and the
    caller surfaces backpressure.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields 1,2,...
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n pages or None (all-or-nothing, so a half-admitted
        request never strands pages)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)


def build_page_table(pages: list[int], max_pages: int) -> np.ndarray:
    """Fixed-width page-table row; unused entries point at garbage page 0."""
    if len(pages) > max_pages:
        raise ValueError(f"{len(pages)} pages exceed table width {max_pages}")
    row = np.zeros((max_pages,), np.int32)
    row[: len(pages)] = pages
    return row
