"""Paged KV cache: device-resident page pool + host-side allocator.

TPU-first replacement for the reference's approach to context (the reference
merely *trims prompts* to fit an external provider's window —
sdk/python/agentfield/agent_ai.py:262-325). Here long sessions keep their KV
resident in HBM pages so agent→agent call chains never re-prefill
(SURVEY §5 "long-context" row, §7 step 7).

Layout: ``[num_layers, num_pages, num_kv_heads, page_size, head_dim]`` —
layers stacked on axis 0 so the decode step scans over them; the trailing
``(page_size, head_dim)`` block is a whole VMEM tile per (page, kv-head), which
is exactly the unit the ragged paged-attention kernel DMAs (Mosaic requires
the last two block dims be full array dims or (8,128)-aligned — the former
``[.., ps, Kh, hd]`` layout forced (1, hd) blocks and failed TPU lowering).
Page 0 is reserved as a garbage sink: inactive decode slots write
there, which keeps the decode step shape-static with no host branching.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, Callable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models.llama import resolve_dtype
from agentfield_tpu.prefix_hash import chain_hash, page_chain_hashes, sketch_digest


@dataclasses.dataclass
class PagedKVCache:
    # Plain [L, P, Kh, ps, hd] arrays, or ops.kv_quant.QuantPages (int8/fp8
    # values + per-slot f32 scales) when kv_quant != "none" — a pytree
    # either way, so jitted scheduler paths carry ONE pool operand.
    k_pages: Any
    v_pages: Any
    page_size: int
    kv_quant: str = "none"

    @property
    def num_pages(self) -> int:
        return jax.tree.leaves(self.k_pages)[0].shape[1]

    @staticmethod
    def create(
        cfg: LlamaConfig,
        num_pages: int,
        page_size: int,
        dtype: str | None = None,
        mesh=None,
        kv_quant: str = "none",
    ) -> "PagedKVCache":
        """With a mesh, pages shard over the KV-head axis on `model` (matching
        the TP sharding of wk/wv, so K/V writes during decode are local — no
        resharding on the hot path). ``kv_quant`` ("int8" | "fp8") stores the
        pages quantized with per-slot scales (docs/KERNELS.md "Quantized
        pages") — roughly double the pages per HBM byte; scales start at 0,
        so fresh pages dequantize to the same zeros a plain pool holds."""
        from agentfield_tpu.ops.kv_quant import QuantPages, quant_value_dtype

        shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim)
        if kv_quant != "none":
            qdt = quant_value_dtype(kv_quant)

            def mk():
                return QuantPages(
                    jnp.zeros(shape, qdt), jnp.zeros(shape[:-1], jnp.float32)
                )

            k, v = mk(), mk()
        else:
            dt = resolve_dtype(dtype or cfg.dtype)
            k = jnp.zeros(shape, dt)
            v = jnp.zeros(shape, dt)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from agentfield_tpu.parallel.mesh import AXIS_MODEL

            if mesh.shape.get(AXIS_MODEL, 1) > 1:
                def place(a):
                    # pages and scales both carry Kh at axis 2
                    spec = P(*([None, None, AXIS_MODEL] + [None] * (a.ndim - 3)))
                    return jax.device_put(a, NamedSharding(mesh, spec))

                k = jax.tree.map(place, k)
                v = jax.tree.map(place, v)
        return PagedKVCache(
            k_pages=k, v_pages=v, page_size=page_size, kv_quant=kv_quant
        )

    def hbm_bytes(self) -> int:
        return 2 * sum(
            a.size * a.dtype.itemsize for a in jax.tree.leaves(self.k_pages)
        )

    def page_bytes(self) -> int:
        """Bytes ONE page occupies across all layers, K+V, including the
        per-slot scales of a quantized pool — the unit the host tier and
        the capacity math budget in."""
        total = 0
        for a in jax.tree.leaves((self.k_pages, self.v_pages)):
            total += (a.size // a.shape[1]) * a.dtype.itemsize
        return total


class PageAllocator:
    """Host-side free-list allocator over the device page pool.

    Page 0 is never handed out (garbage sink for inactive slots). This is the
    TPU analogue of the reference's queue-capacity backpressure
    (reference: internal/handlers/execute.go:333-346 returns HTTP 503 when the
    job queue is full): when no pages are free, admission fails and the
    caller surfaces backpressure.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields 1,2,...
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n pages or None (all-or-nothing, so a half-admitted
        request never strands pages)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)


def build_page_table(pages: list[int], max_pages: int) -> np.ndarray:
    """Fixed-width page-table row; unused entries point at garbage page 0."""
    if len(pages) > max_pages:
        raise ValueError(f"{len(pages)} pages exceed table width {max_pages}")
    row = np.zeros((max_pages,), np.int32)
    row[: len(pages)] = pages
    return row


def pack_ragged_rows(
    rows: Sequence[tuple[np.ndarray, int, Sequence[int]]],
    max_pages: int,
    budget: int,
    block_q: int = 1,
) -> "RaggedRows":
    """Pack ragged ``(page_table_row, start_pos, tokens)`` entries into the
    ragged paged-attention kernel's NATIVE descriptor
    (``ops.paged_attention.RaggedRows``, docs/KERNELS.md): each entry
    becomes ``ceil(len(tokens) / block_q)`` kernel rows of width ``block_q``
    sharing a launch-local ``seq_id``, so an entry's later tokens attend its
    earlier ones through the kernel's same-launch new-key phase. Decode
    entries are 1-token; prefill chunks contribute their whole chunk.

    ``ctx_lens`` is the entry's ``start_pos`` for every row it spans — the
    keys already IN the pool when the launch begins; everything from
    ``start_pos`` on is written BY the launch (the kernel fuses the write).
    Padding rows carry ``n_tokens`` 0 / ``seq_id`` -1 (zero output, no
    writes). Capacity is ``budget`` tokens = ``budget // block_q`` rows;
    overflow raises.
    """
    from agentfield_tpu.ops.paged_attention import RaggedRows

    W = max(1, block_q)
    R = budget // W
    tokens = np.zeros((R, W), np.int32)
    tables = np.zeros((R, max_pages), np.int32)
    row_starts = np.zeros((R,), np.int32)
    n_tokens = np.zeros((R,), np.int32)
    ctx_lens = np.zeros((R,), np.int32)
    seq_ids = np.full((R,), -1, np.int32)
    last_flat: list[int] = []
    r = 0
    for sid, (row, start, toks) in enumerate(rows):
        n = len(toks)
        if n == 0:
            raise ValueError("ragged entry with zero tokens")
        need = -(-n // W)
        if r + need > R:
            raise ValueError(
                f"ragged rows need {r + need}+ rows > capacity "
                f"{R} (budget {budget} / block_q {W})"
            )
        for i in range(need):
            chunk = toks[i * W : (i + 1) * W]
            tokens[r, : len(chunk)] = np.asarray(chunk, np.int32)
            tables[r] = row
            row_starts[r] = start + i * W
            n_tokens[r] = len(chunk)
            ctx_lens[r] = start
            seq_ids[r] = sid
            r += 1
        last_flat.append((r - 1) * W + (n - 1) % W)
    return RaggedRows(
        tokens=tokens,
        page_tables=tables,
        row_starts=row_starts,
        n_tokens=n_tokens,
        ctx_lens=ctx_lens,
        seq_ids=seq_ids,
        last_flat=last_flat,
    )


# chain_hash / page_chain_hashes moved to agentfield_tpu.prefix_hash (the
# gateway's affinity scorer chains the same bytes without importing the
# jax-heavy serving stack); the import above re-exports them for existing
# importers.


def _kv_fault(point: str):
    """Consult the control-plane fault injector WITHOUT importing the (HTTP-
    heavy) control_plane package into every engine process (the engine
    aliases this as _engine_fault — one definition of the activation
    contract): if the faults module was never imported and the env knob is
    unset, no injector can exist and this is two dict lookups."""
    import os
    import sys

    m = sys.modules.get("agentfield_tpu.control_plane.faults")
    if m is None:
        if not os.environ.get("AGENTFIELD_FAULTS"):
            return None
        from agentfield_tpu.control_plane import faults as m
    return m.fire(point)


TIER_HBM = "hbm"
TIER_HOST = "host"

# Bound on queued demotes: each queue entry pins a captured device-side page
# copy until the worker transfers it, so an unbounded queue under a stalled
# worker would silently double the HBM the offload exists to reclaim.
_DEMOTE_QUEUE_MAX = 64


@dataclasses.dataclass
class PageRecord:
    """One content-addressed page: the chain hash that names it and the page
    of token ids backing that hash (kept for collision verification).

    ``tier`` is the record's residence (docs/PREFIX_CACHING.md "Tiered
    cache"): TIER_HBM entries live in a device page (``page`` valid, the
    single-tier behavior); TIER_HOST entries were demoted — their KV sits in
    the pool's host store keyed by ``chain`` and ``page`` is -1 until a
    restore re-adopts them into a freshly allocated HBM page."""

    page: int
    chain: bytes
    tokens: tuple[int, ...]
    last_used: float  # logical LRU clock, maintained by the pool
    tier: str = TIER_HBM
    # Page index within its prefix chain (0 = leading page). The heartbeat
    # sketch orders records by depth so a byte-capped sketch keeps LEADING
    # pages first — a deep entry whose ancestors were dropped can never
    # score (the gateway's consecutive-prefix walk stops at the first miss).
    depth: int = 0


class PrefixPagePool:
    """Refcounted, content-addressed page pool: the cross-request generalization
    of :class:`PageAllocator`.

    Three page states:

    - **free**: on the free list, content is garbage.
    - **live**: refcount >= 1 — owned by one or more slots/sessions. Live pages
      may ALSO be in the content index (a published prompt page of a running
      request), in which case new requests incref them via :meth:`lookup`.
    - **cached**: refcount == 0 but still in the content index — the page's KV
      is valid and reusable. Cached pages sit on an LRU; allocation evicts
      them only when the free list is empty (cached prefixes are a best-effort
      optimization; live requests always win).

    Single ownership rule: every ``alloc``/``lookup`` reference must be
    balanced by one :meth:`free` (release). Over-release raises — the
    refcounted analogue of the old allocator's double-free check.

    Not thread-safe; callers serialize (the engine holds its session lock).
    """

    def __init__(self, num_pages: int, page_size: int, stats: dict | None = None):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        # The pool's bookkeeping is serialized by its OWNER, not in-class
        # (the engine holds _session_lock around every call — see the
        # "guarded by: _session_lock" annotations on the engine's allocator
        # and pool attributes). afcheck's guarded-by pass enforces the
        # corollary it CAN check: nothing outside this class touches these.
        self._refs = [0] * num_pages  # guarded by: external(engine _session_lock)
        # free list; pop() yields 1,2,...
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # guarded by: external(engine _session_lock)
        self._by_hash: dict[bytes, PageRecord] = {}  # guarded by: external(engine _session_lock)
        self._by_page: dict[int, PageRecord] = {}  # guarded by: external(engine _session_lock)
        # refcount-0 cached pages in eviction order (oldest first); OrderedDict
        # gives O(1) touch/evict instead of an O(cached) min() per allocation.
        self._lru: collections.OrderedDict[int, None] = collections.OrderedDict()  # guarded by: external(engine _session_lock)
        self._clock = 0.0
        # Shared counter surface (the engine passes its stats dict so pool
        # events ride heartbeats/metrics without a mirror-copy step).
        self.stats = stats if stats is not None else {}
        for k in (
            "prefix_pages_published",
            "prefix_pages_evicted",
            "prefix_pages_reused",
            # Tiered KV (docs/PREFIX_CACHING.md "Tiered cache") — exported
            # even with the tier off so the /stats→heartbeat→Prometheus
            # pipeline always carries the family:
            "kv_offload_demoted",
            "kv_offload_restored",
            "kv_offload_restore_fail",
            "kv_offload_demote_fail",
            "kv_offload_host_evicted",
            # Restore wall-clock (docs/OBSERVABILITY.md): cumulative ms the
            # batched host→device restore uploads took. With
            # kv_offload_restored it gives avg restore latency — the
            # aggregate twin of the per-request ``engine.kv_restore`` trace
            # span, and the number that says whether a tier restore is
            # still cheaper than the re-prefill it replaces.
            "kv_offload_restore_ms_total",
            # Cluster tier (docs/PREFIX_CACHING.md "Cluster tier"): the
            # heartbeat sketch + cross-node page transfer counter family —
            # always exported so the /stats→heartbeat→Prometheus pipeline
            # carries them even on nodes that never fetch.
            "prefix_sketch_truncated_total",
            "kv_fetch_requested_total",
            "kv_fetch_served_total",
            "kv_fetch_failed_total",
            "kv_fetch_bytes_total",
            "kv_fetch_pages_adopted_total",
            # Quantized KV pages (docs/KERNELS.md "Quantized pages",
            # EngineConfig.kv_quant_dtype): always exported so the
            # stats→heartbeat→Prometheus pipeline carries the family even
            # with quantization off. *_bytes_saved are vs the engine's
            # dense (bf16/f32) page layout at the same page count.
            "kv_quant_pages_total",
            "kv_quant_bytes_saved_total",
            "kv_quant_host_bytes_saved_total",
            "kv_quant_wire_bytes_saved_total",  # incremented by the model
            # node's kv_export_pages (cross-node transfer serving side)
        ):
            self.stats.setdefault(k, 0)
        # Armed by the engine when kv_quant_dtype != none (configure_quant):
        # bytes one quantized page saves vs its dense twin, in HBM and in
        # the host store respectively (same payload → same value today).
        self._quant_hbm_saved = 0
        self._quant_host_saved = 0
        # ---- host (offload) tier — inert until enable_host_tier() wires the
        # device-copy callbacks; every branch below checks _host_enabled so
        # the disabled pool is bit-compatible with the single-tier one.
        self._host_enabled = False
        # Host store: chain hash -> opaque KV payload, insertion-ordered so
        # the oldest demotion evicts first. Together with _lru this forms ONE
        # logical LRU spanning both tiers: demotion moves the LRU's oldest
        # entries here, budget pressure drops this dict's oldest entries.
        self._host: collections.OrderedDict[bytes, Any] = collections.OrderedDict()  # guarded by: external(engine _session_lock)
        self._host_bytes = 0  # guarded by: external(engine _session_lock)
        # Demote queue: (chain, page, captured device handle) awaiting the
        # worker's device→host transfer; _demote_inflight tracks chains
        # queued or mid-copy so a page is never captured twice.
        self._demote_q: collections.deque[tuple[bytes, int, Any]] = collections.deque()  # guarded by: external(engine _session_lock)
        self._demote_inflight: set[bytes] = set()  # guarded by: external(engine _session_lock)
        self._host_budget = 0
        self._page_bytes = 1
        self._demote_watermark = 0
        self._ext_lock: Any = None  # the OWNER's serializer (engine _session_lock)
        self._capture: Callable[[int], Any] | None = None
        self._fetch: Callable[[Any], Any] | None = None
        self._upload: Callable[[list[Any], list[int]], None] | None = None
        self._restore_alloc: Callable[[], list[int] | None] | None = None
        self._offload_wake = threading.Event()
        self._offload_stop = False
        self._offload_thread: threading.Thread | None = None

    # -- gauges ---------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Allocatable pages right now: the free list plus refcount-0 cached
        pages (evictable on demand). This is the backpressure signal."""
        return len(self._free) + len(self._lru)

    @property
    def cached_pages(self) -> int:
        """Pages resident in the content index (live shared + refcount-0)."""
        return len(self._by_page)

    @property
    def host_pages(self) -> int:
        """Host-tier (demoted) entries. These are NOT instantly allocatable
        — each restore consumes a fresh HBM page — so they never count in
        :attr:`free_pages`."""
        return len(self._host)

    @property
    def host_bytes(self) -> int:
        return self._host_bytes

    @property
    def shared_pages(self) -> int:
        """Indexed pages currently referenced by 2+ holders — the live
        sharing factor the whole feature exists for."""
        return sum(1 for p in self._by_page if self._refs[p] > 1)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def is_shared(self, page: int) -> bool:
        """True when writing this page could be observed by someone else:
        it is content-addressed (future lookups may match it) or another
        holder references it. Writers must copy-on-write first."""
        return page in self._by_page or self._refs[page] > 1

    def configure_quant(
        self, hbm_saved_per_page: int, host_saved_per_page: int | None = None
    ) -> None:
        """Arm the quantized-page counters (engine init, kv_quant_dtype !=
        none): every page this pool hands out stores its KV quantized, so
        ``alloc`` counts ``kv_quant_pages_total`` and banks the per-page HBM
        saving; demote commits and peer adoptions bank the host-store
        saving. 0 (the default) keeps the counters inert and the pool
        bit-compatible with the unquantized one."""
        self._quant_hbm_saved = max(0, int(hbm_saved_per_page))
        self._quant_host_saved = (
            self._quant_hbm_saved
            if host_saved_per_page is None
            else max(0, int(host_saved_per_page))
        )

    # -- allocation -----------------------------------------------------

    def _tick(self) -> float:
        self._clock += 1.0
        return self._clock

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n pages (each with refcount 1) or None — all-or-nothing,
        so a half-admitted request never strands pages. Evicts LRU cached
        pages (refcount 0) when the free list runs dry."""
        if n > self.free_pages:
            return None
        out = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._lru.popitem(last=False)  # oldest cached page
                rec = self._by_page.pop(p)
                del self._by_hash[rec.chain]
                self.stats["prefix_pages_evicted"] += 1
            self._refs[p] = 1
            out.append(p)
        if self._quant_hbm_saved:
            # every allocated page stores quantized KV: n pages just cost
            # n * (dense - quant) bytes less HBM than the bf16 pool would
            self.stats["kv_quant_pages_total"] += n
            self.stats["kv_quant_bytes_saved_total"] += n * self._quant_hbm_saved
        if self._host_enabled and len(self._free) < self._demote_watermark:
            # Allocation pressure: start demoting the LRU tail BEFORE the
            # free list runs dry, so the eviction above (which loses the
            # page's KV for good) stays the rare path. The copies run on the
            # offload worker — this only enqueues.
            self.demote_lru(8)
        return out

    def incref(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if self._refs[p] == 0:
                # a cached page gaining a holder leaves the eviction LRU
                if p not in self._by_page:
                    raise ValueError(f"incref of unowned, uncached page {p}")
                self._lru.pop(p, None)
            self._refs[p] += 1

    def free(self, pages: list[int]) -> None:
        """Release one reference per page. Pages hitting refcount 0 return to
        the free list, unless content-addressed — those stay cached (KV still
        valid) until allocation pressure evicts them LRU."""
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if self._refs[p] <= 0:
                raise ValueError(f"over-free of page {p} (refcount already 0)")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                if p in self._by_page:
                    self._lru[p] = None  # newest cached entry
                else:
                    self._free.append(p)

    # -- content index --------------------------------------------------

    def _prefix_chain(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> Iterator[PageRecord]:
        """Walk the longest indexed full-page prefix chain of `tokens`,
        yielding each matching PageRecord. The ONE definition of "what
        counts as a prefix hit" — peek/lookup/evictable_prefix_pages all
        iterate this walk so a probe can never desynchronize from actual
        lookup behavior (the tuple compare guards hash collisions)."""
        ps = self.page_size
        if hashes is None:
            hashes = page_chain_hashes(tokens, ps)
        for i, h in enumerate(hashes):
            rec = self._by_hash.get(h)
            if rec is None or rec.tokens != tuple(tokens[i * ps : (i + 1) * ps]):
                return
            yield rec

    def peek(self, tokens: Sequence[int], hashes: list[bytes] | None = None) -> int:
        """Length (in tokens) of the longest indexed full-page prefix of
        `tokens`, without taking references. Admission uses this to order
        and group candidates before committing. Pass precomputed
        `hashes` (page_chain_hashes) to skip re-hashing."""
        return sum(1 for _ in self._prefix_chain(tokens, hashes)) * self.page_size

    def evictable_prefix_pages(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> int:
        """Of the longest indexed full-page prefix of `tokens`, how many
        pages are refcount-0 (LRU-resident)? Those pages count in
        :attr:`free_pages`, but an admission :meth:`lookup` increfs them OUT
        of the evictable pool — capacity probes that subtract the cached
        prefix from a request's page need must also subtract this overlap
        from ``free_pages``, or they double-count the same pages. HOST-tier
        entries are excluded: a demoted page is not instantly allocatable
        (its restore CONSUMES a fresh page instead of supplying one)."""
        return sum(
            1
            for rec in self._prefix_chain(tokens, hashes)
            if rec.tier == TIER_HBM and self._refs[rec.page] == 0
        )

    def host_prefix_pages(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> int:
        """Of the longest indexed full-page prefix of `tokens`, how many
        entries are HOST-tier? Each such page needs a FRESH HBM page as its
        restore target, so capacity probes must add this count back to the
        request's allocation need (peek() counts host entries as cached).
        Keyed on store occupancy, not the enabled flag: entries demoted
        before a close() still restore (and still cost a page)."""
        if not self._host:
            return 0
        return sum(
            1 for rec in self._prefix_chain(tokens, hashes) if rec.tier == TIER_HOST
        )

    def prefix_overlap_pages(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> tuple[int, int]:
        """(evictable, host) counts of the prompt's indexed prefix in ONE
        chain walk — the pair every starvation probe needs per tick; the
        two single-count methods above remain for callers wanting one."""
        evictable = host = 0
        for rec in self._prefix_chain(tokens, hashes):
            if rec.tier == TIER_HOST:
                host += 1
            elif self._refs[rec.page] == 0:
                evictable += 1
        return evictable, host

    def sketch(self, max_bytes: int) -> dict[str, Any]:
        """Compact summary of the prefix index for heartbeat publication
        (docs/PREFIX_CACHING.md "Cluster tier"): truncated chain-hash digests
        of every indexed record (both tiers — a host-resident page is
        fetchable too), leading pages first. The gateway scores a dispatch
        candidate by walking a request's chain hashes through this digest
        set; consecutive leading hits × page_size ≈ the cached-prefix length
        the node would serve.

        ``max_bytes`` caps the JSON payload (an unbounded index would bloat
        every heartbeat); overflow drops the DEEPEST records first and
        counts ``prefix_sketch_truncated_total`` — a capped sketch under-
        advertises long chains, which only costs routing optimality."""
        # ~19 bytes per digest in the JSON array ("0123456789abcdef", ), plus
        # fixed envelope overhead.
        cap = max(0, (int(max_bytes) - 64) // 19)
        recs = sorted(self._by_hash.values(), key=lambda r: r.depth)
        truncated = len(recs) > cap
        if truncated:
            self.stats["prefix_sketch_truncated_total"] += 1
            recs = recs[:cap]
        return {
            "v": 1,
            "page_size": self.page_size,
            "digests": [sketch_digest(r.chain) for r in recs],
            "truncated": int(truncated),
        }

    def lookup(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> tuple[list[int], int]:
        """Longest indexed full-page chain prefix of `tokens`. Returns
        (pages, matched_token_count); the caller owns one reference on each
        returned page (balance with free()).

        HOST-tier entries restore on the way (host→device copy into freshly
        allocated pages, ONE batched upload per lookup — per-page dispatch
        overhead would eat the saving on short pages) so the caller sees
        ordinary HBM pages; a restore that cannot proceed (no allocatable
        page, injected ``kv.restore_fail``, copy error) truncates the match
        at that page — the caller admits with the shorter prefix and
        re-prefills the rest, token-exact."""
        pages: list[int] = []
        t = self._tick()
        # (record, tentative target page, payload) awaiting the one upload
        pending: list[tuple[PageRecord, int, Any]] = []
        for rec in self._prefix_chain(tokens, hashes):
            if rec.tier == TIER_HOST:
                prep = self._prepare_restore(rec)
                if prep is None:
                    break  # degrade to a plain re-prefill of the remainder
                rec.last_used = t
                pending.append(prep)
                pages.append(prep[1])  # alloc above IS our reference
                continue
            rec.last_used = t
            if self._refs[rec.page] == 0:
                self._lru.pop(rec.page, None)
            self._refs[rec.page] += 1
            pages.append(rec.page)
        if pending and not self._commit_restores(pending):
            # The batched upload failed: truncate the match at the FIRST
            # pending restore — release the tentative pages (never indexed;
            # they go back to the free list) and the references taken on
            # anything matched after that point.
            cut = pages.index(pending[0][1])
            self.free(pages[cut:])
            pages = pages[:cut]
        self.stats["prefix_pages_reused"] += len(pages)
        return pages, len(pages) * self.page_size

    def publish(self, tokens: Sequence[int], pages: list[int]) -> int:
        """Register the full pages of `tokens` (KV resident in position-
        ordered `pages`) under their chain hashes. Pages whose chain is
        already indexed are skipped — a concurrent duplicate prefill keeps
        the incumbent and the duplicate page simply frees when its holder
        releases it. Returns the number of newly indexed pages.

        Publish only pages whose content is FINAL (the engine publishes a
        prompt after its prefill completes, and generated pages at release):
        an indexed page must never be rewritten — writers copy-on-write.
        """
        ps = self.page_size
        h = b""
        n_new = 0
        t = self._tick()
        for i in range(min(len(tokens) // ps, len(pages))):
            page_toks = tuple(tokens[i * ps : (i + 1) * ps])
            h = chain_hash(h, page_toks)
            rec = self._by_hash.get(h)
            if rec is not None:
                if rec.tokens == page_toks:
                    rec.last_used = t
                    if rec.tier == TIER_HOST:
                        # The publisher holds this exact chain's KV in HBM
                        # RIGHT NOW: re-adopt its page instead of keeping the
                        # slower host copy — a free un-demote (the host
                        # payload is dropped; the publisher's release later
                        # lands the page refcount-0 cached as usual).
                        p = pages[i]
                        if p not in self._by_page:
                            if self._host.pop(rec.chain, None) is not None:
                                self._host_bytes -= self._page_bytes
                            rec.tier = TIER_HBM
                            rec.page = p
                            self._by_page[p] = rec
                            if self._refs[p] == 0:
                                self._lru[p] = None
                    elif self._refs[rec.page] == 0:
                        self._lru.move_to_end(rec.page)
                continue  # same chain cached, or a hash collision: keep incumbent
            p = pages[i]
            if p in self._by_page:
                continue  # page already names another chain (defensive)
            self._by_page[p] = self._by_hash[h] = PageRecord(
                page=p, chain=h, tokens=page_toks, last_used=t, depth=i
            )
            if self._refs[p] == 0:
                self._lru[p] = None
            n_new += 1
            self.stats["prefix_pages_published"] += 1
        return n_new

    def park(self, tokens: Sequence[int], pages: list[int]) -> int:
        """Preemption primitive (docs/FAULT_TOLERANCE.md overload control):
        publish the full pages of `tokens` into the content index, then
        release the caller's reference on EVERY page. Indexed pages land on
        the refcount-0 LRU — their KV stays valid and a later lookup (the
        preempted request's resume, or any shared-prefix sibling) reuses
        them without recompute — while partial tail pages (whose content is
        not a full addressable page) return straight to the free list, so
        the preemptor can allocate immediately. Returns the number of pages
        left CACHED (resume's best-case prefix, in pages)."""
        self.publish(tokens, pages)
        self.free(pages)
        return sum(1 for p in pages if p in self._by_page)

    def forget(self, page: int) -> None:
        """Drop a page from the content index (its KV is about to be
        invalidated). Live references are unaffected; a refcount-0 page
        moves from cached to free."""
        rec = self._by_page.pop(page, None)
        if rec is None:
            return
        del self._by_hash[rec.chain]
        if page in self._lru:
            del self._lru[page]
        if self._refs[page] == 0:
            self._free.append(page)

    # -- host (offload) tier -------------------------------------------
    #
    # docs/PREFIX_CACHING.md "Tiered cache". Lifecycle of one page:
    #
    #   HBM cached (refcount-0 LRU)
    #     --enqueue (pressure watermark / idle-session expiry)-->  demote queue
    #     --worker: D2H copy OFF the tick path, then commit under the
    #       external lock (aborts if the page was reused/incref'd/evicted
    #       meanwhile — a stalled or failed copy can never corrupt)-->
    #   HOST (record.tier=HOST, HBM page back on the free list)
    #     --lookup()/session-resume hit: alloc fresh page + H2D copy-->
    #   HBM cached again (restore), or
    #     --host budget pressure: oldest host entry dropped-->  gone.
    #
    # All state below is serialized by the OWNER's lock (the engine's
    # _session_lock, passed to enable_host_tier); the worker takes it only
    # for O(1) queue pops and commits, never across a device copy, so it can
    # never deadlock or stall the scheduler thread.

    def enable_host_tier(
        self,
        *,
        budget_bytes: int,
        page_bytes: int,
        lock: Any,
        capture: Callable[[int], Any],
        fetch: Callable[[Any], Any],
        upload: Callable[[list[Any], list[int]], None],
        restore_alloc: Callable[[], list[int] | None] | None = None,
        watermark: int | None = None,
    ) -> None:
        """Arm the host tier. ``capture(page)`` snapshots a page's KV as an
        opaque device handle (cheap, called under the lock at enqueue time —
        the handle's CONTENT is fixed at capture, so later reuse of the HBM
        page cannot corrupt the copy); ``fetch(handle)`` is the blocking
        device→host transfer (worker thread, no lock held); ``upload
        (payloads, pages)`` is the BATCHED host→device restore — one call
        per lookup, however many pages it matched in the host tier (caller
        thread, under the lock). ``restore_alloc`` supplies the restore's target page —
        the engine passes its session-evicting allocator, because a pool
        fully pinned by LIVE idle sessions would otherwise fail every
        restore (the pool itself cannot evict sessions: they hold live
        references) and silently degrade resumes to re-prefill forever.
        ``lock`` must be the same lock that serializes every other pool
        call."""
        if budget_bytes <= 0:
            raise ValueError(f"budget_bytes={budget_bytes} must be > 0")
        if self._host_enabled:
            raise RuntimeError("host tier already enabled")
        if self._offload_thread is not None:
            # close() timed out on a stalled worker: starting a second one
            # would race the first's eventual commit attempts
            raise RuntimeError("previous offload worker still draining")
        self.enable_restore(
            budget_bytes=budget_bytes,
            page_bytes=page_bytes,
            upload=upload,
            restore_alloc=restore_alloc,
        )
        self._ext_lock = lock
        self._capture, self._fetch = capture, fetch
        # Start demoting while this many free pages remain: early enough
        # that the async copy usually wins the race against hard eviction,
        # late enough that a lightly loaded pool never churns D2H copies.
        self._demote_watermark = (
            watermark if watermark is not None else max(2, self.num_pages // 8)
        )
        self._offload_stop = False  # close() may have armed it: a re-enabled
        # tier must get a worker that actually runs (and commits)
        self._host_enabled = True
        self._offload_thread = threading.Thread(
            target=self._offload_worker, name="kv-offload", daemon=True
        )
        self._offload_thread.start()

    def enable_restore(
        self,
        *,
        budget_bytes: int,
        page_bytes: int,
        upload: Callable[[list[Any], list[int]], None],
        restore_alloc: Callable[[], list[int] | None] | None = None,
    ) -> None:
        """Arm ONLY the host-store restore half of the tier: the upload
        callback, the restore allocator, and a byte budget for host-resident
        payloads — no demote worker, no watermark. This is what the cluster
        tier rides (docs/PREFIX_CACHING.md "Cluster tier"): pages fetched
        from a peer node land in the host store via :meth:`adopt_host_pages`
        and restore through the ordinary lookup path, whether or not the
        local demotion tier is on. ``enable_host_tier`` calls this too, so
        there is exactly one definition of "restore is armed"."""
        if budget_bytes <= 0:
            raise ValueError(f"budget_bytes={budget_bytes} must be > 0")
        if page_bytes <= 0:
            raise ValueError(f"page_bytes={page_bytes} must be > 0")
        self._host_budget = int(budget_bytes)
        self._page_bytes = int(page_bytes)
        self._upload = upload
        self._restore_alloc = restore_alloc

    def adopt_host_pages(
        self, entries: Sequence[tuple[bytes, int, tuple[int, ...], Any]]
    ) -> int:
        """Install peer-fetched KV payloads into the host store (caller
        holds the external lock): each entry is ``(chain, depth, tokens,
        payload)`` exactly as a local demotion would have produced. Chains
        already indexed are skipped — LOCAL content always wins (an HBM or
        host record under this chain is at least as good as the peer copy).
        Adopted entries restore through the ordinary lookup walk at the next
        admission; budget overflow drops the store's oldest entries, same
        rule as demotion. Returns the number adopted.

        Safety: the CALLER derives ``chain``/``tokens`` from its own prompt
        (model_node.prefetch), so a corrupt peer response can only waste
        host-store budget — the content index never lies about what tokens
        a chain names, and lookup() still verifies tokens before any reuse.
        """
        if self._upload is None:
            return 0  # restore never armed: adopted pages could never land
        n = 0
        for chain, depth, tokens, payload in entries:
            if chain in self._by_hash:
                continue
            self._by_hash[chain] = PageRecord(
                page=-1,
                chain=chain,
                tokens=tuple(tokens),
                last_used=self._tick(),
                tier=TIER_HOST,
                depth=int(depth),
            )
            self._host[chain] = payload
            self._host_bytes += self._page_bytes
            n += 1
            self.stats["kv_fetch_pages_adopted_total"] += 1
            if self._quant_host_saved:
                self.stats["kv_quant_host_bytes_saved_total"] += self._quant_host_saved
        self._evict_host_over_budget()
        return n

    def _evict_host_over_budget(self) -> None:  # guarded by: external(engine _session_lock)
        while self._host_bytes > self._host_budget and self._host:
            # Budget pressure drops the OLDEST host entries — the spanning
            # LRU's far end. Gone for real (re-prefill recreates them).
            old_chain, _ = self._host.popitem(last=False)
            self._host_bytes -= self._page_bytes
            self._by_hash.pop(old_chain, None)
            self.stats["kv_offload_host_evicted"] += 1

    def export_prep(
        self, chains: Sequence[bytes], capture: Callable[[int], Any]
    ) -> list[tuple[bytes, int, Any, str]]:
        """Phase 1 of serving a peer's ``kv_fetch`` (caller holds the
        external lock): for each requested chain hash that is indexed,
        return ``(chain, depth, obj, kind)`` — ``("host", payload)`` for
        host-tier entries (wire-ready) or ``("handle", captured slices)``
        for HBM pages. The handle's content is fixed at capture (same
        snapshot semantics as demotion), so the caller materializes the
        device→host copy OUTSIDE the lock without racing the tick path.
        Unknown chains are simply absent from the result — the requester
        treats the response as best-effort."""
        out: list[tuple[bytes, int, Any, str]] = []
        for chain in chains:
            rec = self._by_hash.get(chain)
            if rec is None:
                continue
            if rec.tier == TIER_HOST:
                payload = self._host.get(rec.chain)
                if payload is not None:
                    out.append((rec.chain, rec.depth, payload, "host"))
                continue
            try:
                out.append((rec.chain, rec.depth, capture(rec.page), "handle"))
            except Exception:  # afcheck: ignore[except-swallow] best-effort peer serving: a failed capture shortens the response and the requester re-prefills
                continue
        return out

    def demote_lru(self, n: int | None = None) -> int:
        """Enqueue up to `n` (all, when None) of the OLDEST refcount-0
        cached pages for demotion to the host tier. Returns the number
        enqueued; the copies land asynchronously (offload_drain to wait).

        Runs on the admission hot path (alloc's watermark trigger), so:
        full demote queue → immediate no-op, and the bounded form scans at
        most 4n LRU entries (the oldest few may already be in flight)
        instead of materializing the whole LRU. _enqueue_demote never
        mutates _lru, so iterating the live dict is safe."""
        if not self._host_enabled or len(self._demote_q) >= _DEMOTE_QUEUE_MAX:
            return 0
        scan = iter(self._lru) if n is None else itertools.islice(self._lru, 4 * n)
        count = 0
        for p in scan:
            if n is not None and count >= n:
                break
            if self._enqueue_demote(p):
                count += 1
        return count

    def demote_pages(self, pages: Sequence[int]) -> int:
        """Enqueue specific pages for demotion — the idle-session expiry
        hook (engine.gc_sessions): an expired session's KV should move to
        host RAM, not linger as HBM-evictable until churn drops it. Pages
        that are not refcount-0 indexed entries are skipped."""
        if not self._host_enabled:
            return 0
        return sum(1 for p in pages if self._enqueue_demote(p))

    def _enqueue_demote(self, page: int) -> bool:
        rec = self._by_page.get(page)
        if (
            rec is None
            or self._refs[page] != 0
            or rec.chain in self._demote_inflight
            or len(self._demote_q) >= _DEMOTE_QUEUE_MAX
            or self._page_bytes > self._host_budget
        ):
            return False
        try:
            handle = self._capture(page)
        except Exception:
            self.stats["kv_offload_demote_fail"] += 1
            return False
        self._demote_q.append((rec.chain, page, handle))
        self._demote_inflight.add(rec.chain)
        self._offload_wake.set()
        return True

    def _offload_worker(self) -> None:
        """Drain the demote queue: device→host copy OUTSIDE the lock, O(1)
        commit under it. The only thread besides the pool's owner that
        touches pool state — and only in the two `with` blocks below."""
        while True:
            self._offload_wake.wait(timeout=0.5)
            self._offload_wake.clear()
            if self._offload_stop:
                return
            while True:
                with self._ext_lock:
                    if not self._demote_q:
                        break
                    chain, page, handle = self._demote_q.popleft()
                try:
                    payload = self._fetch(handle)  # blocking D2H, no lock
                except Exception:
                    with self._ext_lock:
                        self._demote_inflight.discard(chain)
                        # demote failure keeps the HBM page: the record was
                        # never touched, the page stays cached/evictable
                        self.stats["kv_offload_demote_fail"] += 1
                    continue
                fault = _kv_fault("kv.offload_stall")
                if fault is not None and fault.delay_s > 0:
                    time.sleep(fault.delay_s)  # chaos: a slow copy — the
                    # pool must keep working on the captured-at-enqueue
                    # snapshot semantics while this sleeps
                with self._ext_lock:
                    self._demote_inflight.discard(chain)
                    self._commit_demote(chain, page, payload)

    def _commit_demote(self, chain: bytes, page: int, payload: Any) -> None:  # guarded by: external(engine _session_lock)
        if self._offload_stop:
            return  # close() promised demotion stops: a worker surfacing
            # from a stalled copy after (or during) close commits nothing
        rec = self._by_hash.get(chain)
        if (
            rec is None
            or rec.tier != TIER_HBM
            or rec.page != page
            or self._refs[page] != 0
        ):
            # The page was evicted, re-allocated, or incref'd while the copy
            # was in flight: the HBM state wins, the copy is discarded. This
            # is the corruption guard the kv.offload_stall chaos test leans
            # on — a late copy commits NOTHING unless the record is exactly
            # as captured.
            return
        self._host[chain] = payload
        self._host_bytes += self._page_bytes
        del self._by_page[page]
        self._lru.pop(page, None)
        self._free.append(page)
        rec.tier = TIER_HOST
        rec.page = -1
        self.stats["kv_offload_demoted"] += 1
        if self._quant_host_saved:
            # a quantized payload presses the host budget at ~half the
            # dense rate: bank the difference for the capacity runbook
            self.stats["kv_quant_host_bytes_saved_total"] += self._quant_host_saved
        self._evict_host_over_budget()

    def _prepare_restore(self, rec: PageRecord) -> tuple[PageRecord, int, Any] | None:
        """Phase 1 of a restore (caller holds the external lock): consult
        the fault schedule, find the payload, and allocate the target page.
        Returns (record, page, payload) for the batched upload, or None —
        in which case the HOST entry is KEPT (a transient failure may
        succeed on the next attempt; a permanently failing entry heals when
        a re-prefill re-publishes the chain, which re-adopts the record
        into HBM and drops the payload)."""
        fault = _kv_fault("kv.restore_fail")
        if fault is not None:
            self.stats["kv_offload_restore_fail"] += 1
            return None
        payload = self._host.get(rec.chain)
        if payload is None:
            return None  # defensive: record/store desync degrades to a miss
        # The engine's allocator can evict idle SESSIONS for the target
        # page (live requests win over cached prefixes — and this restore
        # serves a live request); the plain pool alloc is the fallback.
        got = self._restore_alloc() if self._restore_alloc is not None else self.alloc(1)
        if got is None:
            # No allocatable target page: the caller re-prefills instead.
            # Counted — docs/OPERATIONS.md tells operators a restore_fail
            # spike means "too full to restore into", and sustained page
            # exhaustion is exactly the common real-world shape of that.
            self.stats["kv_offload_restore_fail"] += 1
            return None
        return rec, got[0], payload

    def _commit_restores(self, pending: list[tuple[PageRecord, int, Any]]) -> bool:
        """Phase 2: ONE batched host→device upload for every page the walk
        matched in the host tier, then the index flips. All-or-nothing: on
        upload failure nothing commits (entries kept, caller truncates)."""
        t0 = time.perf_counter()
        try:
            self._upload([p for _, _, p in pending], [pg for _, pg, _ in pending])
        except Exception:
            self.stats["kv_offload_restore_fail"] += 1
            return False
        self.stats["kv_offload_restore_ms_total"] += (
            time.perf_counter() - t0
        ) * 1e3
        for rec, page, _ in pending:
            del self._host[rec.chain]
            self._host_bytes -= self._page_bytes
            rec.tier = TIER_HBM
            rec.page = page
            self._by_page[page] = rec
            self.stats["kv_offload_restored"] += 1
        return True

    def offload_drain(self, timeout: float = 10.0) -> bool:
        """Block until the demote queue is empty and no copy is in flight
        (tests, bench, shutdown). Must be called WITHOUT the external lock
        held — the worker needs it to commit."""
        if not self._host_enabled:
            return True
        deadline = time.monotonic() + timeout
        self._offload_wake.set()
        while time.monotonic() < deadline:
            with self._ext_lock:
                if not self._demote_q and not self._demote_inflight:
                    return True
            time.sleep(0.002)
        return False

    def close(self) -> None:
        """Stop the offload worker (idempotent; no-op when the tier was
        never enabled). The pool remains usable and HOST entries still
        restore on lookup — only DEMOTION stops: the enabled flag drops and
        the queue is cleared, or post-close watermark/expiry triggers would
        keep capturing device page copies into a queue nothing drains."""
        t = self._offload_thread
        if t is None:
            return
        self._offload_stop = True
        self._offload_wake.set()
        # Disarm BEFORE the join: once the stop flag is up, _commit_demote
        # refuses, so even a worker stalled past the join timeout can never
        # demote after close() returns.
        with self._ext_lock:
            self._host_enabled = False
            self._demote_q.clear()  # drop captured device buffers
            self._demote_inflight.clear()
        t.join(timeout=5.0)
        if not t.is_alive():
            # A worker stalled in a long copy keeps its handle: a repeat
            # close() re-joins instead of silently orphaning the thread.
            self._offload_thread = None
