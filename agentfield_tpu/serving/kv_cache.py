"""Paged KV cache: device-resident page pool + host-side allocator.

TPU-first replacement for the reference's approach to context (the reference
merely *trims prompts* to fit an external provider's window —
sdk/python/agentfield/agent_ai.py:262-325). Here long sessions keep their KV
resident in HBM pages so agent→agent call chains never re-prefill
(SURVEY §5 "long-context" row, §7 step 7).

Layout: ``[num_layers, num_pages, num_kv_heads, page_size, head_dim]`` —
layers stacked on axis 0 so the decode step scans over them; the trailing
``(page_size, head_dim)`` block is a whole VMEM tile per (page, kv-head), which
is exactly the unit the Pallas paged-decode kernel DMAs (Mosaic requires the
last two block dims be full array dims or (8,128)-aligned — the former
``[.., ps, Kh, hd]`` layout forced (1, hd) blocks and failed TPU lowering).
Page 0 is reserved as a garbage sink: inactive decode slots write
there, which keeps the decode step shape-static with no host branching.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from agentfield_tpu.models.configs import LlamaConfig
from agentfield_tpu.models.llama import resolve_dtype


@dataclasses.dataclass
class PagedKVCache:
    k_pages: jnp.ndarray  # [L, P, Kh, ps, hd]
    v_pages: jnp.ndarray  # [L, P, Kh, ps, hd]
    page_size: int

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @staticmethod
    def create(
        cfg: LlamaConfig,
        num_pages: int,
        page_size: int,
        dtype: str | None = None,
        mesh=None,
    ) -> "PagedKVCache":
        """With a mesh, pages shard over the KV-head axis on `model` (matching
        the TP sharding of wk/wv, so K/V writes during decode are local — no
        resharding on the hot path)."""
        dt = resolve_dtype(dtype or cfg.dtype)
        shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim)
        k = jnp.zeros(shape, dt)
        v = jnp.zeros(shape, dt)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from agentfield_tpu.parallel.mesh import AXIS_MODEL

            if mesh.shape.get(AXIS_MODEL, 1) > 1:
                s = NamedSharding(mesh, P(None, None, AXIS_MODEL, None, None))
                k, v = jax.device_put(k, s), jax.device_put(v, s)
        return PagedKVCache(k_pages=k, v_pages=v, page_size=page_size)

    def hbm_bytes(self) -> int:
        return 2 * self.k_pages.size * self.k_pages.dtype.itemsize


class PageAllocator:
    """Host-side free-list allocator over the device page pool.

    Page 0 is never handed out (garbage sink for inactive slots). This is the
    TPU analogue of the reference's queue-capacity backpressure
    (reference: internal/handlers/execute.go:333-346 returns HTTP 503 when the
    job queue is full): when no pages are free, admission fails and the
    caller surfaces backpressure.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields 1,2,...
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n pages or None (all-or-nothing, so a half-admitted
        request never strands pages)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)


def build_page_table(pages: list[int], max_pages: int) -> np.ndarray:
    """Fixed-width page-table row; unused entries point at garbage page 0."""
    if len(pages) > max_pages:
        raise ValueError(f"{len(pages)} pages exceed table width {max_pages}")
    row = np.zeros((max_pages,), np.int32)
    row[: len(pages)] = pages
    return row


def pack_ragged_rows(
    rows: Sequence[tuple[np.ndarray, int, Sequence[int]]],
    max_pages: int,
    budget: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Flatten ragged ``(page_table_row, start_pos, tokens)`` descriptors into
    the fixed-width per-token arrays the mixed token-budget forward consumes
    (docs/MIXED_SCHEDULING.md): every token becomes its own n_tokens=1 ragged
    row against its sequence's page table. Decode rows are 1-token
    descriptors; prefill chunks contribute one entry per chunk token.

    Returns ``(tokens [budget], positions [budget], tables [budget, max_pages],
    k_lens [budget])`` — padding entries carry k_len 0 (inactive: attention
    returns zeros, KV writes route to garbage page 0). The multi-row scatter
    install into the paged pool follows from these arrays: token i writes at
    ``(tables[i][positions[i] // page_size], positions[i] % page_size)``.
    """
    tokens = np.zeros((budget,), np.int32)
    positions = np.zeros((budget,), np.int32)
    tables = np.zeros((budget, max_pages), np.int32)
    k_lens = np.zeros((budget,), np.int32)
    idx = 0
    for row, start, toks in rows:
        n = len(toks)
        if idx + n > budget:
            raise ValueError(
                f"ragged rows hold {idx + n}+ tokens > budget {budget}"
            )
        tokens[idx : idx + n] = np.asarray(toks, np.int32)
        positions[idx : idx + n] = start + np.arange(n, dtype=np.int32)
        tables[idx : idx + n] = row
        k_lens[idx : idx + n] = positions[idx : idx + n] + 1
        idx += n
    return tokens, positions, tables, k_lens


def chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    """Chained block hash over one full page of token ids (vLLM/SGLang-style):
    a page's identity is (everything before it, its own tokens), so two
    requests share a page iff their prompts agree on the ENTIRE prefix
    through that page. blake2b-128 makes accidental collisions negligible;
    lookups still verify token content, so a collision degrades to a miss,
    never to wrong KV."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


def page_chain_hashes(tokens: Sequence[int], page_size: int) -> list[bytes]:
    """Chained hash per full page of `tokens`. Callers that probe the index
    repeatedly (the scheduler, every admission tick) compute this once per
    request and pass it to peek()/lookup() instead of re-hashing the prompt
    each tick."""
    out: list[bytes] = []
    h = b""
    for off in range(0, (len(tokens) // page_size) * page_size, page_size):
        h = chain_hash(h, tokens[off : off + page_size])
        out.append(h)
    return out


@dataclasses.dataclass
class PageRecord:
    """One content-addressed page: the chain hash that names it and the page
    of token ids backing that hash (kept for collision verification)."""

    page: int
    chain: bytes
    tokens: tuple[int, ...]
    last_used: float  # logical LRU clock, maintained by the pool


class PrefixPagePool:
    """Refcounted, content-addressed page pool: the cross-request generalization
    of :class:`PageAllocator`.

    Three page states:

    - **free**: on the free list, content is garbage.
    - **live**: refcount >= 1 — owned by one or more slots/sessions. Live pages
      may ALSO be in the content index (a published prompt page of a running
      request), in which case new requests incref them via :meth:`lookup`.
    - **cached**: refcount == 0 but still in the content index — the page's KV
      is valid and reusable. Cached pages sit on an LRU; allocation evicts
      them only when the free list is empty (cached prefixes are a best-effort
      optimization; live requests always win).

    Single ownership rule: every ``alloc``/``lookup`` reference must be
    balanced by one :meth:`free` (release). Over-release raises — the
    refcounted analogue of the old allocator's double-free check.

    Not thread-safe; callers serialize (the engine holds its session lock).
    """

    def __init__(self, num_pages: int, page_size: int, stats: dict | None = None):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        # The pool's bookkeeping is serialized by its OWNER, not in-class
        # (the engine holds _session_lock around every call — see the
        # "guarded by: _session_lock" annotations on the engine's allocator
        # and pool attributes). afcheck's guarded-by pass enforces the
        # corollary it CAN check: nothing outside this class touches these.
        self._refs = [0] * num_pages  # guarded by: external(engine _session_lock)
        # free list; pop() yields 1,2,...
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # guarded by: external(engine _session_lock)
        self._by_hash: dict[bytes, PageRecord] = {}  # guarded by: external(engine _session_lock)
        self._by_page: dict[int, PageRecord] = {}  # guarded by: external(engine _session_lock)
        # refcount-0 cached pages in eviction order (oldest first); OrderedDict
        # gives O(1) touch/evict instead of an O(cached) min() per allocation.
        self._lru: collections.OrderedDict[int, None] = collections.OrderedDict()  # guarded by: external(engine _session_lock)
        self._clock = 0.0
        # Shared counter surface (the engine passes its stats dict so pool
        # events ride heartbeats/metrics without a mirror-copy step).
        self.stats = stats if stats is not None else {}
        for k in ("prefix_pages_published", "prefix_pages_evicted", "prefix_pages_reused"):
            self.stats.setdefault(k, 0)

    # -- gauges ---------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Allocatable pages right now: the free list plus refcount-0 cached
        pages (evictable on demand). This is the backpressure signal."""
        return len(self._free) + len(self._lru)

    @property
    def cached_pages(self) -> int:
        """Pages resident in the content index (live shared + refcount-0)."""
        return len(self._by_page)

    @property
    def shared_pages(self) -> int:
        """Indexed pages currently referenced by 2+ holders — the live
        sharing factor the whole feature exists for."""
        return sum(1 for p in self._by_page if self._refs[p] > 1)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def is_shared(self, page: int) -> bool:
        """True when writing this page could be observed by someone else:
        it is content-addressed (future lookups may match it) or another
        holder references it. Writers must copy-on-write first."""
        return page in self._by_page or self._refs[page] > 1

    # -- allocation -----------------------------------------------------

    def _tick(self) -> float:
        self._clock += 1.0
        return self._clock

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n pages (each with refcount 1) or None — all-or-nothing,
        so a half-admitted request never strands pages. Evicts LRU cached
        pages (refcount 0) when the free list runs dry."""
        if n > self.free_pages:
            return None
        out = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._lru.popitem(last=False)  # oldest cached page
                rec = self._by_page.pop(p)
                del self._by_hash[rec.chain]
                self.stats["prefix_pages_evicted"] += 1
            self._refs[p] = 1
            out.append(p)
        return out

    def incref(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if self._refs[p] == 0:
                # a cached page gaining a holder leaves the eviction LRU
                if p not in self._by_page:
                    raise ValueError(f"incref of unowned, uncached page {p}")
                self._lru.pop(p, None)
            self._refs[p] += 1

    def free(self, pages: list[int]) -> None:
        """Release one reference per page. Pages hitting refcount 0 return to
        the free list, unless content-addressed — those stay cached (KV still
        valid) until allocation pressure evicts them LRU."""
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"invalid page id {p}")
            if self._refs[p] <= 0:
                raise ValueError(f"over-free of page {p} (refcount already 0)")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                if p in self._by_page:
                    self._lru[p] = None  # newest cached entry
                else:
                    self._free.append(p)

    # -- content index --------------------------------------------------

    def _prefix_chain(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> Iterator[PageRecord]:
        """Walk the longest indexed full-page prefix chain of `tokens`,
        yielding each matching PageRecord. The ONE definition of "what
        counts as a prefix hit" — peek/lookup/evictable_prefix_pages all
        iterate this walk so a probe can never desynchronize from actual
        lookup behavior (the tuple compare guards hash collisions)."""
        ps = self.page_size
        if hashes is None:
            hashes = page_chain_hashes(tokens, ps)
        for i, h in enumerate(hashes):
            rec = self._by_hash.get(h)
            if rec is None or rec.tokens != tuple(tokens[i * ps : (i + 1) * ps]):
                return
            yield rec

    def peek(self, tokens: Sequence[int], hashes: list[bytes] | None = None) -> int:
        """Length (in tokens) of the longest indexed full-page prefix of
        `tokens`, without taking references. Admission uses this to order
        and group candidates before committing. Pass precomputed
        `hashes` (page_chain_hashes) to skip re-hashing."""
        return sum(1 for _ in self._prefix_chain(tokens, hashes)) * self.page_size

    def evictable_prefix_pages(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> int:
        """Of the longest indexed full-page prefix of `tokens`, how many
        pages are refcount-0 (LRU-resident)? Those pages count in
        :attr:`free_pages`, but an admission :meth:`lookup` increfs them OUT
        of the evictable pool — capacity probes that subtract the cached
        prefix from a request's page need must also subtract this overlap
        from ``free_pages``, or they double-count the same pages."""
        return sum(
            1
            for rec in self._prefix_chain(tokens, hashes)
            if self._refs[rec.page] == 0
        )

    def lookup(
        self, tokens: Sequence[int], hashes: list[bytes] | None = None
    ) -> tuple[list[int], int]:
        """Longest indexed full-page chain prefix of `tokens`. Returns
        (pages, matched_token_count); the caller owns one reference on each
        returned page (balance with free())."""
        pages: list[int] = []
        t = self._tick()
        for rec in self._prefix_chain(tokens, hashes):
            rec.last_used = t
            if self._refs[rec.page] == 0:
                self._lru.pop(rec.page, None)
            self._refs[rec.page] += 1
            pages.append(rec.page)
        self.stats["prefix_pages_reused"] += len(pages)
        return pages, len(pages) * self.page_size

    def publish(self, tokens: Sequence[int], pages: list[int]) -> int:
        """Register the full pages of `tokens` (KV resident in position-
        ordered `pages`) under their chain hashes. Pages whose chain is
        already indexed are skipped — a concurrent duplicate prefill keeps
        the incumbent and the duplicate page simply frees when its holder
        releases it. Returns the number of newly indexed pages.

        Publish only pages whose content is FINAL (the engine publishes a
        prompt after its prefill completes, and generated pages at release):
        an indexed page must never be rewritten — writers copy-on-write.
        """
        ps = self.page_size
        h = b""
        n_new = 0
        t = self._tick()
        for i in range(min(len(tokens) // ps, len(pages))):
            page_toks = tuple(tokens[i * ps : (i + 1) * ps])
            h = chain_hash(h, page_toks)
            rec = self._by_hash.get(h)
            if rec is not None:
                if rec.tokens == page_toks:
                    rec.last_used = t
                    if self._refs[rec.page] == 0:
                        self._lru.move_to_end(rec.page)
                continue  # same chain cached, or a hash collision: keep incumbent
            p = pages[i]
            if p in self._by_page:
                continue  # page already names another chain (defensive)
            self._by_page[p] = self._by_hash[h] = PageRecord(
                page=p, chain=h, tokens=page_toks, last_used=t
            )
            if self._refs[p] == 0:
                self._lru[p] = None
            n_new += 1
            self.stats["prefix_pages_published"] += 1
        return n_new

    def park(self, tokens: Sequence[int], pages: list[int]) -> int:
        """Preemption primitive (docs/FAULT_TOLERANCE.md overload control):
        publish the full pages of `tokens` into the content index, then
        release the caller's reference on EVERY page. Indexed pages land on
        the refcount-0 LRU — their KV stays valid and a later lookup (the
        preempted request's resume, or any shared-prefix sibling) reuses
        them without recompute — while partial tail pages (whose content is
        not a full addressable page) return straight to the free list, so
        the preemptor can allocate immediately. Returns the number of pages
        left CACHED (resume's best-case prefix, in pages)."""
        self.publish(tokens, pages)
        self.free(pages)
        return sum(1 for p in pages if p in self._by_page)

    def forget(self, page: int) -> None:
        """Drop a page from the content index (its KV is about to be
        invalidated). Live references are unaffected; a refcount-0 page
        moves from cached to free."""
        rec = self._by_page.pop(page, None)
        if rec is None:
            return
        del self._by_hash[rec.chain]
        if page in self._lru:
            del self._lru[page]
        if self._refs[page] == 0:
            self._free.append(page)
