// Package agent is the Go SDK for the agentfield_tpu control plane — the
// second-language counterpart of the Python SDK (agentfield_tpu/sdk) and the
// C++ SDK (native/sdk/afagent.hpp), playing the reference Go SDK's role
// (reference: sdk/go/agent/agent.go:93 — register reasoners, HTTP server,
// control-plane registration + heartbeat, gateway Call, ai client).
//
// Wire protocol (pinned by the control plane, control_plane/server.py):
//
//	outbound POST {cp}/api/v1/nodes                    registration (201)
//	         POST {cp}/api/v1/nodes/{id}/heartbeat     2s cadence; 404 → re-register
//	         POST {cp}/api/v1/execute/{target}         gateway execute
//	inbound  POST /reasoners/{id}  {"input":..., "execution_id":...}
//	         → 200 {"result":...} | 500 {"error":...}
//	         GET  /health          → {"status":"ok","node_id":...}
package agent

import (
	"bufio"
	"bytes"
	"context"
	"encoding/json"
	"errors"
	"fmt"
	"net"
	"net/http"
	"strings"
	"sync"
	"time"
)

// Handler is a reasoner/skill implementation: JSON-decoded input in, any
// JSON-encodable result out.
type Handler func(ctx context.Context, input map[string]any) (any, error)

type component struct {
	id          string
	kind        string // "reasoner" | "skill"
	description string
	fn          Handler
}

// ExecutionContext carries the X-* identity headers the control plane
// propagates across calls (agentfield_tpu/sdk/context.py).
type ExecutionContext struct {
	RunID             string
	ExecutionID       string
	ParentExecutionID string
	SessionID         string
	ActorID           string
}

type ctxKey struct{}

func contextWith(ctx context.Context, ec ExecutionContext) context.Context {
	return context.WithValue(ctx, ctxKey{}, ec)
}

// ExecutionContextFrom recovers the propagated identity inside a Handler.
func ExecutionContextFrom(ctx context.Context) (ExecutionContext, bool) {
	ec, ok := ctx.Value(ctxKey{}).(ExecutionContext)
	return ec, ok
}

// Agent registers with a control plane, serves its components over HTTP, and
// heartbeats. Zero value is not usable — construct with New.
type Agent struct {
	NodeID       string
	ControlPlane string
	Metadata     map[string]any

	mu         sync.Mutex
	components map[string]component
	server     *http.Server
	listener   net.Listener
	baseURL    string
	hbStop     chan struct{}
	hbDone     chan struct{}
	client     *http.Client
}

// New builds an agent bound to a control plane base URL (no trailing slash).
func New(nodeID, controlPlane string) (*Agent, error) {
	if nodeID == "" || strings.Contains(nodeID, ".") {
		return nil, fmt.Errorf("node_id %q must be non-empty and contain no '.'", nodeID)
	}
	return &Agent{
		NodeID:       nodeID,
		ControlPlane: strings.TrimRight(controlPlane, "/"),
		Metadata:     map[string]any{"sdk": "go"},
		components:   map[string]component{},
		client:       &http.Client{Timeout: 90 * time.Second},
	}, nil
}

// RegisterReasoner adds a reasoner; call before Start.
func (a *Agent) RegisterReasoner(id, description string, fn Handler) {
	a.register(component{id: id, kind: "reasoner", description: description, fn: fn})
}

// RegisterSkill adds a skill; call before Start.
func (a *Agent) RegisterSkill(id, description string, fn Handler) {
	a.register(component{id: id, kind: "skill", description: description, fn: fn})
}

func (a *Agent) register(c component) {
	a.mu.Lock()
	defer a.mu.Unlock()
	a.components[c.id] = c
}

// Start binds 127.0.0.1:0, registers with the control plane, and begins
// heartbeating. Returns once the node is registered.
func (a *Agent) Start(ctx context.Context) error {
	ln, err := net.Listen("tcp", "127.0.0.1:0")
	if err != nil {
		return err
	}
	a.listener = ln
	a.baseURL = "http://" + ln.Addr().String()
	mux := http.NewServeMux()
	mux.HandleFunc("/health", a.handleHealth)
	mux.HandleFunc("/reasoners/", a.handleInvoke)
	mux.HandleFunc("/skills/", a.handleInvoke)
	a.server = &http.Server{Handler: mux}
	go a.server.Serve(ln) //nolint:errcheck // closed via Shutdown

	if err := a.doRegister(ctx); err != nil {
		_ = a.server.Close()
		return err
	}
	a.hbStop = make(chan struct{})
	a.hbDone = make(chan struct{})
	go a.heartbeatLoop()
	return nil
}

// Stop shuts the HTTP server and heartbeat down.
func (a *Agent) Stop(ctx context.Context) error {
	if a.hbStop != nil {
		close(a.hbStop)
		<-a.hbDone
		a.hbStop = nil
	}
	if a.server != nil {
		return a.server.Shutdown(ctx)
	}
	return nil
}

// BaseURL is the bound address after Start (for tests).
func (a *Agent) BaseURL() string { return a.baseURL }

func (a *Agent) doRegister(ctx context.Context) error {
	a.mu.Lock()
	var reasoners, skills []map[string]any
	for _, c := range a.components {
		entry := map[string]any{"id": c.id, "description": c.description}
		if c.kind == "skill" {
			skills = append(skills, entry)
		} else {
			reasoners = append(reasoners, entry)
		}
	}
	a.mu.Unlock()
	body := map[string]any{
		"node_id":   a.NodeID,
		"base_url":  a.baseURL,
		"metadata":  a.Metadata,
		"reasoners": reasoners,
		"skills":    skills,
	}
	resp, raw, err := a.postJSON(ctx, a.ControlPlane+"/api/v1/nodes", body)
	if err != nil {
		return err
	}
	if resp != http.StatusCreated {
		return fmt.Errorf("registration failed: %d %s", resp, raw)
	}
	return nil
}

func (a *Agent) heartbeatLoop() {
	defer close(a.hbDone)
	t := time.NewTicker(2 * time.Second)
	defer t.Stop()
	for {
		select {
		case <-a.hbStop:
			return
		case <-t.C:
			ctx, cancel := context.WithTimeout(context.Background(), 5*time.Second)
			status, _, err := a.postJSON(ctx, a.ControlPlane+"/api/v1/nodes/"+a.NodeID+"/heartbeat", map[string]any{})
			if err == nil && status == http.StatusNotFound {
				// control plane restarted: re-register (Python SDK parity)
				_ = a.doRegister(ctx)
			}
			cancel()
		}
	}
}

func (a *Agent) handleHealth(w http.ResponseWriter, _ *http.Request) {
	writeJSON(w, http.StatusOK, map[string]any{"status": "ok", "node_id": a.NodeID})
}

type invokeBody struct {
	Input       map[string]any `json:"input"`
	ExecutionID string         `json:"execution_id"`
	RunID       string         `json:"run_id"`
}

func (a *Agent) handleInvoke(w http.ResponseWriter, r *http.Request) {
	if r.Method != http.MethodPost {
		writeJSON(w, http.StatusMethodNotAllowed, map[string]any{"error": "POST only"})
		return
	}
	parts := strings.Split(strings.Trim(r.URL.Path, "/"), "/")
	if len(parts) != 2 {
		writeJSON(w, http.StatusNotFound, map[string]any{"error": "not found"})
		return
	}
	a.mu.Lock()
	c, ok := a.components[parts[1]]
	a.mu.Unlock()
	if !ok {
		writeJSON(w, http.StatusNotFound, map[string]any{"error": "unknown component " + parts[1]})
		return
	}
	var body invokeBody
	if err := json.NewDecoder(r.Body).Decode(&body); err != nil {
		writeJSON(w, http.StatusBadRequest, map[string]any{"error": "bad JSON: " + err.Error()})
		return
	}
	ec := ExecutionContext{
		RunID:             firstNonEmpty(r.Header.Get("X-Run-ID"), body.RunID),
		ExecutionID:       firstNonEmpty(r.Header.Get("X-Execution-ID"), body.ExecutionID),
		ParentExecutionID: r.Header.Get("X-Parent-Execution-ID"),
		SessionID:         r.Header.Get("X-Session-ID"),
		ActorID:           r.Header.Get("X-Actor-ID"),
	}
	result, err := c.fn(contextWith(r.Context(), ec), body.Input)
	if err != nil {
		writeJSON(w, http.StatusInternalServerError, map[string]any{"error": err.Error()})
		return
	}
	writeJSON(w, http.StatusOK, map[string]any{"result": result})
}

// Call executes a target ("node.component") through the gateway and returns
// the terminal execution document's result (reference Call, agent.go:514).
func (a *Agent) Call(ctx context.Context, target string, input map[string]any) (map[string]any, error) {
	doc, err := a.Execute(ctx, target, input)
	if err != nil {
		return nil, err
	}
	if status, _ := doc["status"].(string); status != "completed" {
		return nil, fmt.Errorf("execution %v: %v", doc["status"], doc["error"])
	}
	result, _ := doc["result"].(map[string]any)
	if result == nil {
		// non-object results wrap so callers always get a map
		return map[string]any{"result": doc["result"]}, nil
	}
	return result, nil
}

// Execute posts to the gateway and returns the raw execution document.
func (a *Agent) Execute(ctx context.Context, target string, input map[string]any) (map[string]any, error) {
	status, raw, err := a.postJSON(ctx, a.ControlPlane+"/api/v1/execute/"+target, map[string]any{"input": input})
	if err != nil {
		return nil, err
	}
	var doc map[string]any
	if err := json.Unmarshal(raw, &doc); err != nil {
		return nil, fmt.Errorf("gateway returned %d with non-JSON body", status)
	}
	if status >= 400 {
		return doc, fmt.Errorf("gateway %d: %v", status, doc["error"])
	}
	return doc, nil
}

// AiOptions tune an Ai / AiChat / AiStream call.
type AiOptions struct {
	MaxNewTokens int     // default 64
	Temperature  float64 // default 0 (greedy)
	ModelNode    string  // pin a node id; empty resolves the first active model node
}

// Message is one chat turn (role: system | user | assistant).
type Message struct {
	Role    string `json:"role"`
	Content string `json:"content"`
}

// AiResponse is the decoded result of Ai.
type AiResponse struct {
	Text   string
	Model  string
	Tokens []int
}

// Ai runs an LLM call through the gateway to an in-tree model node — the
// reference Go SDK's ai.Client role (sdk/go/ai/client.go) served in-cluster.
// Retries 503/queue-full backpressure with capped exponential backoff.
func (a *Agent) Ai(ctx context.Context, prompt string, opts *AiOptions) (*AiResponse, error) {
	return a.aiRequest(ctx, map[string]any{"prompt": prompt}, opts)
}

// AiChat runs the chat form (reference CompleteWithMessages,
// sdk/go/ai/client.go:61): the model node applies its tokenizer's chat
// template to the messages.
func (a *Agent) AiChat(ctx context.Context, messages []Message, opts *AiOptions) (*AiResponse, error) {
	if len(messages) == 0 {
		return nil, errors.New("messages must be non-empty")
	}
	return a.aiRequest(ctx, map[string]any{"messages": messages}, opts)
}

func (a *Agent) aiRequest(ctx context.Context, input map[string]any, opts *AiOptions) (*AiResponse, error) {
	o := withDefaults(opts)
	node := o.ModelNode
	if node == "" {
		var err error
		if node, _, err = a.resolveModelNode(ctx, ""); err != nil {
			return nil, err
		}
	}
	payload := map[string]any{
		"max_new_tokens": o.MaxNewTokens,
		"temperature":    o.Temperature,
	}
	for k, v := range input {
		payload[k] = v
	}
	delay := 200 * time.Millisecond
	var doc map[string]any
	var err error
	for attempt := 0; attempt < 6; attempt++ {
		var status int
		var raw []byte
		status, raw, err = a.postJSON(ctx, a.ControlPlane+"/api/v1/execute/"+node+".generate", map[string]any{"input": payload})
		if err != nil {
			return nil, err
		}
		if err := json.Unmarshal(raw, &doc); err != nil {
			return nil, fmt.Errorf("gateway returned %d with non-JSON body", status)
		}
		errStr, _ := doc["error"].(string)
		backpressure := status == http.StatusServiceUnavailable ||
			(strings.Contains(errStr, "QueueFullError") &&
				(doc["status"] == "failed" || doc["status"] == "dead_letter"))
		if !backpressure {
			break
		}
		select {
		case <-ctx.Done():
			return nil, ctx.Err()
		case <-time.After(delay):
		}
		if delay < 5*time.Second {
			delay *= 2
		}
	}
	if doc["status"] != "completed" {
		return nil, fmt.Errorf("ai failed: %v", doc["error"])
	}
	result, _ := doc["result"].(map[string]any)
	out := &AiResponse{}
	out.Text, _ = result["text"].(string)
	out.Model, _ = result["model"].(string)
	if toks, ok := result["tokens"].([]any); ok {
		for _, t := range toks {
			if f, ok := t.(float64); ok {
				out.Tokens = append(out.Tokens, int(f))
			}
		}
	}
	return out, nil
}

// StreamEvent is one token frame from the model node's SSE stream.
type StreamEvent struct {
	Token        int    `json:"token"`
	Index        int    `json:"index"`
	Finished     bool   `json:"finished"`
	FinishReason string `json:"finish_reason"`
	Text         string `json:"text"`
}

// AiStream streams tokens straight from the MODEL NODE's /generate/stream
// SSE endpoint (data plane — tokens never proxy through the control plane;
// the registry only resolves the node's base_url). Return false from fn to
// stop: closing the connection cancels the request server-side.
func (a *Agent) AiStream(ctx context.Context, prompt string, opts *AiOptions, fn func(StreamEvent) bool) (string, error) {
	o := withDefaults(opts)
	node, baseURL, err := a.resolveModelNode(ctx, o.ModelNode)
	if err != nil {
		return "", err
	}
	_ = node
	payload, _ := json.Marshal(map[string]any{
		"prompt":         prompt,
		"max_new_tokens": o.MaxNewTokens,
		"temperature":    o.Temperature,
	})
	req, err := http.NewRequestWithContext(ctx, http.MethodPost, baseURL+"/generate/stream", bytes.NewReader(payload))
	if err != nil {
		return "", err
	}
	req.Header.Set("Content-Type", "application/json")
	resp, err := a.client.Do(req)
	if err != nil {
		return "", err
	}
	defer resp.Body.Close()
	if resp.StatusCode != http.StatusOK {
		return "", fmt.Errorf("stream endpoint returned %d", resp.StatusCode)
	}
	var text strings.Builder
	sc := bufio.NewScanner(resp.Body)
	sc.Buffer(make([]byte, 0, 64*1024), 1<<20)
	for sc.Scan() {
		line := sc.Text()
		if !strings.HasPrefix(line, "data: ") {
			continue
		}
		var ev StreamEvent
		if err := json.Unmarshal([]byte(line[len("data: "):]), &ev); err != nil {
			continue // control frames / keepalives
		}
		text.WriteString(ev.Text)
		if !fn(ev) {
			return text.String(), nil // close cancels server-side
		}
		if ev.Finished {
			return text.String(), nil
		}
	}
	if err := sc.Err(); err != nil {
		return text.String(), err
	}
	return text.String(), errors.New("stream ended before a finished frame")
}

// resolveModelNode finds an active kind=model node (or validates a pinned
// one) and returns (node_id, base_url).
func (a *Agent) resolveModelNode(ctx context.Context, pin string) (string, string, error) {
	req, err := http.NewRequestWithContext(ctx, http.MethodGet, a.ControlPlane+"/api/v1/nodes", nil)
	if err != nil {
		return "", "", err
	}
	resp, err := a.client.Do(req)
	if err != nil {
		return "", "", err
	}
	defer resp.Body.Close()
	var doc struct {
		Nodes []struct {
			NodeID  string         `json:"node_id"`
			Kind    string         `json:"kind"`
			Status  string         `json:"status"`
			BaseURL string         `json:"base_url"`
			Meta    map[string]any `json:"metadata"`
		} `json:"nodes"`
	}
	if err := json.NewDecoder(resp.Body).Decode(&doc); err != nil {
		return "", "", err
	}
	for _, n := range doc.Nodes {
		if n.Kind != "model" || n.Status != "active" {
			continue
		}
		if pin == "" || n.NodeID == pin {
			return n.NodeID, n.BaseURL, nil
		}
	}
	if pin != "" {
		return "", "", fmt.Errorf("model node %q not active", pin)
	}
	return "", "", errors.New("no active model node registered")
}

func (a *Agent) postJSON(ctx context.Context, url string, body any) (int, []byte, error) {
	raw, err := json.Marshal(body)
	if err != nil {
		return 0, nil, err
	}
	req, err := http.NewRequestWithContext(ctx, http.MethodPost, url, bytes.NewReader(raw))
	if err != nil {
		return 0, nil, err
	}
	req.Header.Set("Content-Type", "application/json")
	resp, err := a.client.Do(req)
	if err != nil {
		return 0, nil, err
	}
	defer resp.Body.Close()
	var buf bytes.Buffer
	if _, err := buf.ReadFrom(resp.Body); err != nil {
		return resp.StatusCode, nil, err
	}
	return resp.StatusCode, buf.Bytes(), nil
}

func withDefaults(o *AiOptions) AiOptions {
	out := AiOptions{MaxNewTokens: 64}
	if o != nil {
		out = *o
		if out.MaxNewTokens == 0 {
			out.MaxNewTokens = 64
		}
	}
	return out
}

func writeJSON(w http.ResponseWriter, status int, body any) {
	w.Header().Set("Content-Type", "application/json")
	w.WriteHeader(status)
	_ = json.NewEncoder(w).Encode(body)
}

func firstNonEmpty(vals ...string) string {
	for _, v := range vals {
		if v != "" {
			return v
		}
	}
	return ""
}
