package agent

import (
	"context"
	"encoding/json"
	"fmt"
	"net/http"
	"net/http/httptest"
	"strings"
	"sync/atomic"
	"testing"
	"time"
)

// fakeCP is an httptest stand-in for the control plane's registration,
// heartbeat, node-list and execute surfaces (the Go twin of the Python
// tests' CPHarness, scoped to what this SDK touches).
type fakeCP struct {
	srv          *httptest.Server
	registered   atomic.Int64
	heartbeats   atomic.Int64
	modelURL     string
	lastGenerate atomic.Pointer[map[string]any]
}

func newFakeCP(t *testing.T) *fakeCP {
	f := &fakeCP{}
	mux := http.NewServeMux()
	mux.HandleFunc("/api/v1/nodes", func(w http.ResponseWriter, r *http.Request) {
		if r.Method == http.MethodPost {
			f.registered.Add(1)
			w.WriteHeader(http.StatusCreated)
			_, _ = w.Write([]byte(`{"node_id": "ok"}`))
			return
		}
		_ = json.NewEncoder(w).Encode(map[string]any{"nodes": []map[string]any{
			{"node_id": "m", "kind": "model", "status": "active", "base_url": f.modelURL},
		}})
	})
	mux.HandleFunc("/api/v1/nodes/", func(w http.ResponseWriter, _ *http.Request) {
		f.heartbeats.Add(1)
		_, _ = w.Write([]byte(`{}`))
	})
	mux.HandleFunc("/api/v1/execute/", func(w http.ResponseWriter, r *http.Request) {
		target := strings.TrimPrefix(r.URL.Path, "/api/v1/execute/")
		var body struct {
			Input map[string]any `json:"input"`
		}
		_ = json.NewDecoder(r.Body).Decode(&body)
		switch {
		case target == "m.generate":
			f.lastGenerate.Store(&body.Input)
			_ = json.NewEncoder(w).Encode(map[string]any{
				"status": "completed",
				"result": map[string]any{"text": "hi", "model": "tiny", "tokens": []int{1, 2, 3}},
			})
		case target == "other.echo":
			_ = json.NewEncoder(w).Encode(map[string]any{
				"status": "completed",
				"result": map[string]any{"echo": body.Input["x"]},
			})
		default:
			w.WriteHeader(http.StatusNotFound)
			_ = json.NewEncoder(w).Encode(map[string]any{"error": "unknown target " + target})
		}
	})
	f.srv = httptest.NewServer(mux)
	t.Cleanup(f.srv.Close)
	return f
}

func TestRegisterServeInvoke(t *testing.T) {
	cp := newFakeCP(t)
	a, err := New("go-agent", cp.srv.URL)
	if err != nil {
		t.Fatal(err)
	}
	a.RegisterReasoner("sum", "adds", func(ctx context.Context, in map[string]any) (any, error) {
		ec, ok := ExecutionContextFrom(ctx)
		if !ok || ec.ExecutionID == "" {
			return nil, fmt.Errorf("execution context missing")
		}
		av, _ := in["a"].(float64)
		bv, _ := in["b"].(float64)
		return map[string]any{"sum": av + bv}, nil
	})
	ctx := context.Background()
	if err := a.Start(ctx); err != nil {
		t.Fatal(err)
	}
	defer a.Stop(ctx) //nolint:errcheck
	if cp.registered.Load() != 1 {
		t.Fatalf("registered %d times", cp.registered.Load())
	}
	// invoke like the gateway does
	resp, err := http.Post(a.BaseURL()+"/reasoners/sum", "application/json",
		strings.NewReader(`{"input": {"a": 2, "b": 3}, "execution_id": "e1"}`))
	if err != nil {
		t.Fatal(err)
	}
	defer resp.Body.Close()
	var out struct {
		Result map[string]any `json:"result"`
	}
	if err := json.NewDecoder(resp.Body).Decode(&out); err != nil {
		t.Fatal(err)
	}
	if out.Result["sum"].(float64) != 5 {
		t.Fatalf("sum = %v", out.Result["sum"])
	}
	// handler errors surface as 500 {"error"}
	resp2, _ := http.Post(a.BaseURL()+"/reasoners/missing", "application/json", strings.NewReader(`{}`))
	defer resp2.Body.Close()
	if resp2.StatusCode != http.StatusNotFound {
		t.Fatalf("missing component -> %d", resp2.StatusCode)
	}
}

func TestCallAndAi(t *testing.T) {
	cp := newFakeCP(t)
	a, _ := New("caller", cp.srv.URL)
	ctx := context.Background()
	if err := a.Start(ctx); err != nil {
		t.Fatal(err)
	}
	defer a.Stop(ctx) //nolint:errcheck

	out, err := a.Call(ctx, "other.echo", map[string]any{"x": "y"})
	if err != nil {
		t.Fatal(err)
	}
	if out["echo"] != "y" {
		t.Fatalf("echo = %v", out["echo"])
	}
	if _, err := a.Call(ctx, "nope.nope", nil); err == nil {
		t.Fatal("unknown target must error")
	}

	ai, err := a.Ai(ctx, "hello", nil)
	if err != nil {
		t.Fatal(err)
	}
	if ai.Text != "hi" || ai.Model != "tiny" || len(ai.Tokens) != 3 {
		t.Fatalf("ai = %+v", ai)
	}
}

func TestAiStream(t *testing.T) {
	// model node stand-in: SSE frames, default json.dumps-style separators
	node := httptest.NewServer(http.HandlerFunc(func(w http.ResponseWriter, r *http.Request) {
		if r.URL.Path != "/generate/stream" {
			http.NotFound(w, r)
			return
		}
		w.Header().Set("Content-Type", "text/event-stream")
		fl := w.(http.Flusher)
		for i := 0; i < 3; i++ {
			fin := "false"
			if i == 2 {
				fin = "true"
			}
			fmt.Fprintf(w, "data: {\"token\": %d, \"index\": %d, \"finished\": %s, \"text\": \"t%d\"}\n\n", 100+i, i, fin, i)
			fl.Flush()
		}
	}))
	defer node.Close()
	cp := newFakeCP(t)
	cp.modelURL = node.URL

	a, _ := New("streamer", cp.srv.URL)
	ctx := context.Background()
	if err := a.Start(ctx); err != nil {
		t.Fatal(err)
	}
	defer a.Stop(ctx) //nolint:errcheck
	var events []StreamEvent
	text, err := a.AiStream(ctx, "go", nil, func(ev StreamEvent) bool {
		events = append(events, ev)
		return true
	})
	if err != nil {
		t.Fatal(err)
	}
	if text != "t0t1t2" || len(events) != 3 || !events[2].Finished {
		t.Fatalf("text=%q events=%+v", text, events)
	}
}

func TestHeartbeatReRegistersOn404(t *testing.T) {
	var registered atomic.Int64
	var gone atomic.Bool
	mux := http.NewServeMux()
	mux.HandleFunc("/api/v1/nodes", func(w http.ResponseWriter, r *http.Request) {
		registered.Add(1)
		w.WriteHeader(http.StatusCreated)
	})
	mux.HandleFunc("/api/v1/nodes/", func(w http.ResponseWriter, _ *http.Request) {
		if gone.Load() {
			gone.Store(false)
			w.WriteHeader(http.StatusNotFound)
			return
		}
		_, _ = w.Write([]byte(`{}`))
	})
	srv := httptest.NewServer(mux)
	defer srv.Close()
	a, _ := New("hb", srv.URL)
	ctx := context.Background()
	if err := a.Start(ctx); err != nil {
		t.Fatal(err)
	}
	defer a.Stop(ctx) //nolint:errcheck
	gone.Store(true) // next heartbeat sees 404 → re-register
	deadline := time.Now().Add(10 * time.Second)
	for registered.Load() < 2 && time.Now().Before(deadline) {
		time.Sleep(50 * time.Millisecond)
	}
	if registered.Load() < 2 {
		t.Fatalf("re-registration never happened (%d)", registered.Load())
	}
}

func TestAiChat(t *testing.T) {
	cp := newFakeCP(t)
	a, _ := New("chatter", cp.srv.URL)
	ctx := context.Background()
	if err := a.Start(ctx); err != nil {
		t.Fatal(err)
	}
	defer a.Stop(ctx) //nolint:errcheck
	out, err := a.AiChat(ctx, []Message{{Role: "user", Content: "hi"}}, nil)
	if err != nil {
		t.Fatal(err)
	}
	if out.Text != "hi" {
		t.Fatalf("chat text = %q", out.Text)
	}
	// the wire payload must carry messages (not prompt)
	sent := cp.lastGenerate.Load()
	if sent == nil {
		t.Fatal("generate payload not captured")
	}
	if _, hasPrompt := (*sent)["prompt"]; hasPrompt {
		t.Fatalf("chat payload carries prompt: %v", *sent)
	}
	msgs, ok := (*sent)["messages"].([]any)
	if !ok || len(msgs) != 1 {
		t.Fatalf("messages missing from payload: %v", *sent)
	}
	first, _ := msgs[0].(map[string]any)
	if first["role"] != "user" || first["content"] != "hi" {
		t.Fatalf("bad message encoding: %v", msgs[0])
	}
	if _, err := a.AiChat(ctx, nil, nil); err == nil {
		t.Fatal("empty messages must error")
	}
}
