module agentfield-tpu/sdk/go

go 1.21
