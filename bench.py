"""Headline benchmark: continuous-batching decode throughput on one chip.

Mirrors BASELINE.json's north star (Agent.ai() served in-tree instead of via
litellm): N concurrent reasoner-style requests coalesced into shared decode
steps. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N/3000, ...}
vs_baseline is against the 3,000 tok/s/chip north-star target (BASELINE.md).

Env knobs: AGENTFIELD_BENCH_CPU=1 (debug on CPU), AGENTFIELD_BENCH_MODEL,
AGENTFIELD_BENCH_REQUESTS, AGENTFIELD_BENCH_BATCH.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_done = threading.Event()


def _watchdog(seconds: float) -> None:
    """The TPU tunnel in this environment can wedge at first computation
    (claim never granted). A hung bench must still honor the one-JSON-line
    contract: report the outage and exit instead of blocking the driver."""
    if not _done.wait(seconds):
        print(
            json.dumps(
                {
                    "metric": "decode_throughput_unavailable",
                    "value": 0,
                    "unit": "tok/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"bench did not complete within {seconds:.0f}s "
                    "(TPU backend likely unavailable/wedged)",
                }
            ),
            flush=True,
        )
        os._exit(2)


def main() -> None:
    watchdog_s = float(os.environ.get("AGENTFIELD_BENCH_WATCHDOG", "900"))
    if watchdog_s > 0:  # <= 0 disables the watchdog
        threading.Thread(target=_watchdog, args=(watchdog_s,), daemon=True).start()
    if os.environ.get("AGENTFIELD_BENCH_CPU") == "1":
        from agentfield_tpu._compat import force_cpu_backend

        force_cpu_backend()

    import jax
    import jax.numpy as jnp

    from agentfield_tpu.models import get_config, init_params
    from agentfield_tpu.serving import EngineConfig, InferenceEngine, Request, SamplingParams

    model = os.environ.get("AGENTFIELD_BENCH_MODEL", "llama-3.2-1b")
    n_requests = int(os.environ.get("AGENTFIELD_BENCH_REQUESTS", "256"))
    max_batch = int(os.environ.get("AGENTFIELD_BENCH_BATCH", "64"))
    attn = os.environ.get("AGENTFIELD_BENCH_ATTN", "ref")  # "ref" | "pallas"
    prompt_len, new_tokens = 128, 128

    cfg = get_config(model)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(
        max_batch=max_batch,
        page_size=32,
        num_pages=max_batch * 8 * 2 + 1,
        max_pages_per_seq=8,  # 256-token context budget per request
        max_pending=max(n_requests, 1024),
        attn_impl="pallas" if attn == "pallas" else "ref",
        prefill_impl="flash" if attn == "pallas" else "ref",
    )

    def make_reqs(prefix: str, n: int):
        key = jax.random.PRNGKey(1)
        toks = jax.random.randint(key, (n, prompt_len), 0, cfg.vocab_size, jnp.int32)
        return [
            Request(
                id=f"{prefix}{i}",
                prompt=toks[i].tolist(),
                sampling=SamplingParams(max_new_tokens=new_tokens),
            )
            for i in range(n)
        ]

    # Warmup: trigger prefill-bucket + decode compiles.
    warm = InferenceEngine(params, cfg, ecfg)
    for ev in warm.run_to_completion(make_reqs("w", 2)):
        pass

    # TTFT: idle engine, one request, time submit -> first token.
    ttfts = []
    for i in range(3):
        e = InferenceEngine(params, cfg, ecfg)
        [req] = make_reqs(f"t{i}", 1)
        t0 = time.perf_counter()
        e.submit(req)
        while not e.step():
            pass
        ttfts.append((time.perf_counter() - t0) * 1e3)
    ttft_ms = sorted(ttfts)[len(ttfts) // 2]

    # Throughput: drain n_requests through max_batch decode slots.
    engine = InferenceEngine(params, cfg, ecfg)
    reqs = make_reqs("r", n_requests)
    t0 = time.perf_counter()
    results = engine.run_to_completion(reqs)
    elapsed = time.perf_counter() - t0
    total_tokens = sum(len(v) for v in results.values())
    tok_s = total_tokens / elapsed

    print(
        json.dumps(
            {
                "metric": f"decode_throughput_{model}_continuous_batching_{n_requests}req",
                "value": round(tok_s, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(tok_s / 3000.0, 3),
                "ttft_ms_p50": round(ttft_ms, 1),
                "total_tokens": total_tokens,
                "elapsed_s": round(elapsed, 2),
                "decode_steps": engine.stats["decode_steps"],
                "attn_impl": ecfg.attn_impl,
                "prefill_impl": ecfg.prefill_impl,
                "max_batch": max_batch,
                "device": str(jax.devices()[0]),
            }
        )
    )
    _done.set()


if __name__ == "__main__":
    sys.exit(main())
